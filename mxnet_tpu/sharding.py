"""GSPMD-native sharding engine: declarative partition rules over named
parameter trees (ROADMAP 2 — "the refactor that unlocks pod scale").

The reference scales by hand-built per-model shard code (each parallel
lane wires its own 2-axis mesh: dp×tp, dp×pp, dp×ep, dp×sp) and dense
replication rides the kvstore.  The GSPMD approach (Xu et al. 2021)
inverts that: models declare a *layout* — regex rules over their named
parameter tree mapping params to logical mesh axes — and XLA's SPMD
partitioner materializes the parallelism (sharded matmuls, the gradient
psum-scatters, the resharding collectives) from nothing but input/output
shardings on one jitted program.  This module is that layer:

 - ``match_partition_rules(rules, params)`` — the fmengine pattern
   (SNIPPETS [3]): first ``re.search`` match wins, scalar leaves are
   never partitioned, unmatched params fall back to replication (bit
   identity with the unsharded run) or raise under
   ``on_unmatched='error'``.
 - ``LOGICAL_AXES`` — the axis-name vocabulary rules may speak
   (``dp``/``tp``/``sp``/…); a rule naming an axis outside it is a typo
   and raises at rule-compile time, while a *matched* axis the current
   mesh doesn't carry simply degrades to unsharded, so one rule set runs
   unchanged from a laptop to a pod slice.
 - rule packs for the zoo (``llama_rules``, ``bert_rules``,
   ``transformer_rules``) sharing ``DEFAULT_TAIL`` (embedding /
   layernorm / bias defaults) — these subsume the per-model
   ``apply_tp_shardings`` bodies, which now delegate here.
 - ``resolve_spec(spec, mesh, shape)`` — logical spec → concrete
   ``NamedSharding`` with degradation (absent mesh axes, indivisible
   dims) counted in ``mxnet_sharding_fallback_params_total``.

Consumers: ``parallel.TrainStep(partition_rules=...)`` resolves per-param
NamedShardings at trace time (params AND same-shaped optimizer state),
``gluon.Trainer`` skips the kvstore allreduce for params the mesh already
reduces (``Parameter.mesh_reduced``), and ``mx.checkpoint`` round-trips
sharded params (gather-on-save by default, sharded-save under
``MXNET_CHECKPOINT_SHARDED=1``).
"""

from __future__ import annotations

import re

from .base import MXNetError
from . import telemetry as _tel
from .telemetry import tracer as _ttrace

__all__ = ["LOGICAL_AXES", "match_partition_rules", "apply_rules",
           "resolve_spec", "rule_pack", "llama_rules", "bert_rules",
           "transformer_rules", "llama_fsdp_rules", "bert_fsdp_rules",
           "transformer_fsdp_rules", "DEFAULT_TAIL", "FSDP_TAIL",
           "mark_mesh_reduced"]

# The logical-axis vocabulary rules may name.  Convention (the scaling
# playbook): outermost axis = data parallel (DCN-friendly), inner axes =
# tensor/sequence parallel (ICI-local).
LOGICAL_AXES = {
    "dp": "data parallel — batch dim; grads psum over it",
    "tp": "tensor (megatron) parallel — matmul in/out-feature dims",
    "sp": "sequence/context parallel — the sequence dim of activations",
    "pp": "pipeline parallel — layer/stage dim (pipeline.gpipe)",
    "ep": "expert parallel — the expert dim of MoE stacks",
    "mp": "generic model parallel — coarse table splits (examples)",
    "fsdp": "fully-sharded data parallel — param shards gathered at use",
}

_M_RESOLVED = _tel.counter(
    "mxnet_sharding_resolved_params_total",
    "Params whose partition rule resolved to a sharded NamedSharding.")
_M_FALLBACK = _tel.counter(
    "mxnet_sharding_fallback_params_total",
    "Params that fell back to replication (no rule matched, mesh lacked "
    "the axis, or a dim was not divisible by its mesh axes).")
_M_SKIPPED_ALLREDUCE = _tel.counter(
    "mxnet_sharding_skipped_allreduce_total",
    "Params gluon.Trainer skipped in the kvstore allreduce because the "
    "mesh computation already reduced their gradients (mesh_reduced).")


def _axes_of(entry):
    """The axis names inside one PartitionSpec entry (str | tuple | None)."""
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


def _check_rules(rules):
    """Compile patterns and validate specs against LOGICAL_AXES once."""
    compiled = []
    for i, (pattern, spec) in enumerate(rules):
        try:
            pat = re.compile(pattern)
        except re.error as exc:
            raise MXNetError(
                f"partition rule {i} has an invalid regex "
                f"{pattern!r}: {exc}") from exc
        spec = tuple(spec)
        for entry in spec:
            for axis in _axes_of(entry):
                if axis not in LOGICAL_AXES:
                    raise MXNetError(
                        f"partition rule {pattern!r} names unknown logical "
                        f"axis {axis!r}; vocabulary: "
                        f"{sorted(LOGICAL_AXES)}")
        compiled.append((pat, spec))
    return compiled


def _named_leaves(params):
    """name -> shape-bearing leaf, from a net, ParameterDict, or dict."""
    if hasattr(params, "collect_params"):
        params = params.collect_params()
    if hasattr(params, "items"):
        return dict(params.items())
    raise MXNetError(
        "match_partition_rules wants a Block, ParameterDict, or "
        f"name->param dict; got {type(params).__name__}")


def _shape_of(name, leaf):
    if isinstance(leaf, (tuple, list)):
        return tuple(leaf)
    shape = getattr(leaf, "shape", None)
    if shape is None:
        raise MXNetError(
            f"param {name!r} has no resolved shape (deferred init?) — run "
            "a forward pass before matching partition rules")
    return tuple(shape)


def match_partition_rules(rules, params, on_unmatched="replicate"):
    """Map a named param tree to partition specs, first match wins.

    ``rules`` is an ordered list of ``(regex, spec)`` where ``spec`` is a
    per-dim tuple of logical axis names (or ``None``, or a tuple of axes
    for a dim sharded over several).  ``params`` is a Block,
    ParameterDict, or ``name -> leaf`` dict (leaves need ``.shape``; a
    plain shape tuple also works).  Returns ``{name: spec}``.

    Semantics (the fmengine recipe):
     - scalar leaves (ndim 0 or one element) are never partitioned;
     - the FIRST rule whose regex ``re.search``-matches the name wins;
     - a matched spec longer than the leaf's rank is a layout bug and
       raises;
     - unmatched params replicate (``spec ()``, bit-identical to the
       dense run) — or raise when ``on_unmatched='error'``.
    """
    if on_unmatched not in ("replicate", "error"):
        raise MXNetError(
            f"on_unmatched must be 'replicate' or 'error', "
            f"got {on_unmatched!r}")
    compiled = _check_rules(rules)
    out = {}
    unmatched = []
    for name, leaf in _named_leaves(params).items():
        shape = _shape_of(name, leaf)
        size = 1
        for s in shape:
            size *= s
        if len(shape) == 0 or size == 1:
            out[name] = ()          # never partition scalars
            continue
        for pat, spec in compiled:
            if pat.search(name) is not None:
                if len(spec) > len(shape):
                    raise MXNetError(
                        f"partition rule {pat.pattern!r} has spec {spec} "
                        f"of rank {len(spec)} but param {name!r} has "
                        f"shape {shape}")
                out[name] = spec
                break
        else:
            unmatched.append(name)
            out[name] = ()
    if unmatched and on_unmatched == "error":
        raise MXNetError(
            f"no partition rule matched params {sorted(unmatched)} "
            "(on_unmatched='error')")
    return out


def resolve_spec(spec, mesh, shape=None):
    """Logical spec -> concrete ``NamedSharding`` on ``mesh``.

    Degradation (counted in ``mxnet_sharding_fallback_params_total``):
    axes the mesh doesn't carry drop to unsharded, and — when ``shape``
    is given — a dim not divisible by the product of its mesh axis sizes
    drops to unsharded, so the same rule set runs bit-identically on
    meshes too small (or shapes too ragged) to shard.  Returns the
    sharding and whether anything actually sharded.
    """
    resolved = []
    for d, entry in enumerate(tuple(spec or ())):
        axes = tuple(a for a in _axes_of(entry) if a in mesh.axis_names)
        if axes and shape is not None:
            n = 1
            for a in axes:
                n *= mesh.axis_size(a)
            if shape[d] % n != 0:
                axes = ()       # indivisible dim: degrade to unsharded
        if not axes:
            resolved.append(None)
        elif len(axes) == 1:
            resolved.append(axes[0])
        else:
            resolved.append(axes)
    sharded = any(a is not None for a in resolved)
    if _ttrace._ENABLED:
        (_M_RESOLVED if sharded else _M_FALLBACK).inc()
    if not sharded:
        return mesh.replicated(), False
    return mesh.sharded(*resolved), True


def apply_rules(net_or_params, rules, on_unmatched="replicate",
                mesh_reduced=None):
    """Match ``rules`` and store each spec as ``Parameter.sharding``.

    The hints are consumed by ``parallel.TrainStep`` (and anything else
    reading ``Parameter.sharding``); empty specs clear the hint.  When
    ``mesh_reduced`` is not None every parameter's ``mesh_reduced`` flag
    is set to it (see :func:`mark_mesh_reduced`).  Returns the
    ``{name: spec}`` mapping.
    """
    leaves = _named_leaves(net_or_params)
    specs = match_partition_rules(rules, leaves, on_unmatched=on_unmatched)
    for name, p in leaves.items():
        p.sharding = specs[name] or None
        if mesh_reduced is not None:
            p.mesh_reduced = bool(mesh_reduced)
    return specs


def mark_mesh_reduced(net_or_params, value=True):
    """Flag params whose gradients a mesh computation already reduces.

    A train step jitted over a mesh (``parallel.TrainStep``) comes back
    with globally-reduced gradients — GSPMD inserted the psum(-scatter)
    over the data axis.  A local/device kvstore reduction over the same
    devices would double-count, so ``gluon.Trainer`` skips flagged params
    in its allreduce (non-dist stores only; cross-process reduction is
    still the dist store's job).  Gate: ``MXNET_SHARDING_SKIP_ALLREDUCE``.
    """
    for _, p in _named_leaves(net_or_params).items():
        p.mesh_reduced = bool(value)


# --------------------------------------------------------------------------
# rule packs for the zoo (megatron layouts over Gluon's flat param names)
# --------------------------------------------------------------------------

def DEFAULT_TAIL(tp="tp"):
    """Embedding / layernorm / bias defaults shared by the packs.

    Vocab-dim sharding for embedding tables (column-parallel output
    embeddings), replication for norm scales and biases — append AFTER
    model-specific rules so first-match-wins keeps the specific layout.
    """
    return [
        (r"(tok|word|embed)[a-z0-9]*_weight$", (tp, None)),
        (r"(gamma|beta)$", ()),
        (r"norm_weight$", ()),
        (r"_bias$", ()),
    ]


def llama_rules(tp="tp"):
    """Megatron TP layout for the llama GQA decoder (model_zoo.llama).

    Column-parallel (out-features): q/k/v, gate, up, lm_head; GQA k/v
    shard their ``hd * kv_heads`` dim the same way.  Row-parallel
    (in-features): o_proj, down.  ``tok_weight`` must precede the
    ``k_weight$`` search (first-match-wins is the guard: 'tok_weight'
    ends with 'k_weight' too), which DEFAULT_TAIL's embedding rule and
    its position here make explicit.
    """
    return [
        (r"tok_weight$", (tp, None)),
        (r"(q|k|v|gate|up|lm_head)_weight$", (tp, None)),
        (r"(o|down)_weight$", (None, tp)),
    ] + DEFAULT_TAIL(tp)


def bert_rules(tp="tp"):
    """Megatron TP layout for the BERT encoder (model_zoo.bert):
    qkv + ffn1 column-parallel, attn proj + ffn2 row-parallel,
    word/decoder tables vocab-sharded, everything else replicated."""
    return [
        (r"(attn_qkv|ffn1)_weight$", (tp, None)),
        (r"(attn_proj|ffn2)_weight$", (None, tp)),
        (r"decoder_weight$", (tp, None)),
        (r"position_weight$", ()),
    ] + DEFAULT_TAIL(tp)


def transformer_rules(tp="tp"):
    """Megatron TP layout for the MT transformer (model_zoo.transformer):
    fused self/cross qkv + ffn1 column-parallel, output projections +
    ffn2 row-parallel, embeddings vocab-sharded via the tail."""
    return [
        (r"(attn_qkv|self_qkv|cross_q|cross_kv|ffn1)_weight$", (tp, None)),
        (r"(attn_proj|self_proj|cross_proj|ffn2)_weight$", (None, tp)),
    ] + DEFAULT_TAIL(tp)


# --------------------------------------------------------------------------
# fsdp (ZeRO-3) rule packs — ISSUE 14 tentpole layer 1
# --------------------------------------------------------------------------
#
# fsdp shards PARAMETERS along the data axis (ZeRO-3 / GSPMD "fully
# sharded" recipe): every matmul weight stores only 1/|fsdp| of its
# elements per device and XLA inserts the all-gather right before use
# (and the reduce-scatter on the gradient), so weight + adam-state + grad
# memory divides by the fsdp axis size while the math stays the dense
# math modulo collective reassociation.  Optimizer state rides the owner
# param's layout exactly as with tp (TrainStep._shardings), so m/v shard
# for free.  Composition contract with tp on the SAME mesh: the tp axis
# keeps the megatron dim it owns and fsdp takes the OTHER matmul dim —
# one rule set covers dp-only, +fsdp, and +tp+fsdp meshes because
# resolve_spec degrades any axis the mesh doesn't carry.
#
# Norm scales and biases stay replicated (FSDP_TAIL): they are O(d) while
# the win is the O(d^2) matmuls, and sharding them would make every
# norm a gather for bytes that round to zero.

def FSDP_TAIL(fsdp="fsdp", tp="tp"):
    """Embedding / norm / bias tail for the fsdp packs: embedding tables
    shard vocab over tp AND fsdp (both dims huge), norms/biases
    replicate."""
    return [
        (r"(tok|word|embed)[a-z0-9]*_weight$", ((tp, fsdp), None)),
        (r"(gamma|beta)$", ()),
        (r"norm_weight$", ()),
        (r"_bias$", ()),
    ]


def llama_fsdp_rules(fsdp="fsdp", tp="tp"):
    """ZeRO-3 layout for the llama GQA decoder, composable with tp.

    Column-parallel weights (out, in) keep tp on dim0 and shard dim1
    over fsdp; row-parallel (o/down) the mirror.  On a mesh without tp
    the specs degrade to pure fsdp sharding; without fsdp they degrade
    to llama_rules' tp layout; with neither, full replication — the
    one-rule-set-per-model contract."""
    return [
        (r"tok_weight$", ((tp, fsdp), None)),
        (r"(q|k|v|gate|up|lm_head)_weight$", (tp, fsdp)),
        (r"(o|down)_weight$", (fsdp, tp)),
    ] + FSDP_TAIL(fsdp, tp)


def bert_fsdp_rules(fsdp="fsdp", tp="tp"):
    """ZeRO-3 layout for the BERT encoder (bert_rules + fsdp on the
    non-tp matmul dim)."""
    return [
        (r"(attn_qkv|ffn1)_weight$", (tp, fsdp)),
        (r"(attn_proj|ffn2)_weight$", (fsdp, tp)),
        (r"decoder_weight$", (tp, fsdp)),
        (r"position_weight$", ()),
    ] + FSDP_TAIL(fsdp, tp)


def transformer_fsdp_rules(fsdp="fsdp", tp="tp"):
    """ZeRO-3 layout for the MT transformer (transformer_rules + fsdp
    on the non-tp matmul dim)."""
    return [
        (r"(attn_qkv|self_qkv|cross_q|cross_kv|ffn1)_weight$",
         (tp, fsdp)),
        (r"(attn_proj|self_proj|cross_proj|ffn2)_weight$", (fsdp, tp)),
    ] + FSDP_TAIL(fsdp, tp)


_RULE_PACKS = {
    "llama": llama_rules,
    "bert": bert_rules,
    "transformer": transformer_rules,
}

_FSDP_PACKS = {
    "llama_fsdp": llama_fsdp_rules,
    "bert_fsdp": bert_fsdp_rules,
    "transformer_fsdp": transformer_fsdp_rules,
}


def rule_pack(name, tp="tp", fsdp="fsdp"):
    """A named zoo rule pack: ``rule_pack('llama')``,
    ``rule_pack('llama_fsdp')`` etc."""
    if name in _RULE_PACKS:
        return _RULE_PACKS[name](tp=tp)
    if name in _FSDP_PACKS:
        return _FSDP_PACKS[name](fsdp=fsdp, tp=tp)
    raise MXNetError(
        f"unknown rule pack {name!r}; options "
        f"{sorted(_RULE_PACKS) + sorted(_FSDP_PACKS)}")
