"""Test utilities (reference python/mxnet/test_utils.py, SURVEY §4.2).

The numeric oracles the reference test-suite is built on:
``assert_almost_equal`` (dtype-aware tolerances), ``check_numeric_gradient``
(finite differences vs autograd), ``check_consistency`` (same graph across
contexts — THE cpu↔tpu kernel oracle), ``default_context`` (the ctx-injection
point the whole suite parameterizes over), random array generators.
"""

from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .context import Context, current_context, cpu
from . import ndarray as nd
from .ndarray.ndarray import NDArray

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "same", "rand_ndarray", "rand_shape_2d",
           "rand_shape_3d", "rand_shape_nd", "check_numeric_gradient",
           "check_consistency", "default_rtols", "effective_dtype"]

_default_ctx = None


def default_context():
    return _default_ctx if _default_ctx is not None else current_context()


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx
    Context._default_ctx.value = ctx


_RTOLS = {
    _np.dtype(_np.float16): 1e-2,
    _np.dtype(_np.float32): 1e-4,
    _np.dtype(_np.float64): 1e-6,
}
_ATOLS = {
    _np.dtype(_np.float16): 1e-3,
    _np.dtype(_np.float32): 1e-5,
    _np.dtype(_np.float64): 1e-8,
}
try:
    from .base import bfloat16 as _bf16
    if _bf16 is not None:
        _RTOLS[_np.dtype(_bf16)] = 2e-2
        _ATOLS[_np.dtype(_bf16)] = 2e-2
except ImportError:
    pass


def effective_dtype(arr):
    return _np.dtype(arr.dtype)


def default_rtols(a=None, b=None):
    cands = [x for x in (a, b) if x is not None]
    rtol = max((_RTOLS.get(effective_dtype(x), 1e-4) for x in cands),
               default=1e-4)
    atol = max((_ATOLS.get(effective_dtype(x), 1e-5) for x in cands),
               default=1e-5)
    return rtol, atol


def _to_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def same(a, b):
    return _np.array_equal(_to_np(a), _to_np(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    a, b = _to_np(a), _to_np(b)
    if rtol is None or atol is None:
        drtol, datol = default_rtols(a, b)
        rtol = rtol if rtol is not None else drtol
        atol = atol if atol is not None else datol
    return _np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    an, bn = _to_np(a), _to_np(b)
    if rtol is None or atol is None:
        drtol, datol = default_rtols(an, bn)
        rtol = rtol if rtol is not None else drtol
        atol = atol if atol is not None else datol
    if not _np.allclose(an, bn, rtol=rtol, atol=atol, equal_nan=equal_nan):
        diff = _np.abs(an.astype(_np.float64) - bn.astype(_np.float64))
        rel = diff / (_np.abs(bn).astype(_np.float64) + atol)
        raise AssertionError(
            f"{names[0]} and {names[1]} differ: max abs {diff.max():.3g}, "
            f"max rel {rel.max():.3g} (rtol={rtol}, atol={atol})\n"
            f"{names[0]}: {an.ravel()[:8]}...\n{names[1]}: {bn.ravel()[:8]}...")


def rand_shape_2d(dim0=10, dim1=10):
    return (_np.random.randint(1, dim0 + 1), _np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_np.random.randint(1, dim0 + 1), _np.random.randint(1, dim1 + 1),
            _np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(_np.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=_np.float32,
                 ctx=None):
    if stype == "default":
        return nd.array(_np.random.uniform(-1, 1, shape).astype(dtype),
                        ctx=ctx)
    from .ndarray import sparse as sp
    density = density if density is not None else 0.5
    arr = _np.random.uniform(-1, 1, shape).astype(dtype)
    mask = _np.random.uniform(0, 1, shape[0]) < density
    arr[~mask] = 0
    if stype == "row_sparse":
        return sp.row_sparse_array(arr, ctx=ctx)
    if stype == "csr":
        flat_mask = _np.random.uniform(0, 1, shape) < density
        arr = arr * flat_mask
        return sp.csr_matrix(arr, ctx=ctx)
    raise MXNetError(f"unknown stype {stype}")


def check_numeric_gradient(f, inputs, eps=1e-3, rtol=1e-2, atol=1e-3):
    """Finite differences vs autograd on scalar-valued f(inputs)->NDArray."""
    from . import autograd
    ins = [x if isinstance(x, NDArray) else nd.array(x) for x in inputs]
    for x in ins:
        x.attach_grad()
    with autograd.record():
        y = f(*ins)
        if y.size != 1:
            y = y.sum()
    y.backward()
    for i, x in enumerate(ins):
        xn = x.asnumpy().astype(_np.float64)
        num = _np.zeros_like(xn)
        for idx in _np.ndindex(*xn.shape):
            xp = xn.copy()
            xp[idx] += eps
            xm = xn.copy()
            xm[idx] -= eps
            args_p = [nd.array(xp.astype(x.dtype)) if j == i else ins[j]
                      for j in range(len(ins))]
            args_m = [nd.array(xm.astype(x.dtype)) if j == i else ins[j]
                      for j in range(len(ins))]
            fp = float(f(*args_p).sum().asnumpy())
            fm = float(f(*args_m).sum().asnumpy())
            num[idx] = (fp - fm) / (2 * eps)
        assert_almost_equal(x.grad.asnumpy(), num, rtol=rtol, atol=atol,
                            names=(f"autograd[{i}]", f"numeric[{i}]"))


def check_consistency(f, inputs_np, ctx_list=None, rtol=None, atol=None):
    """Run the same computation on every context and cross-check — the
    reference's cpu↔gpu oracle, now cpu↔tpu (SURVEY §4.2)."""
    if ctx_list is None:
        ctx_list = [cpu()]
        from .context import num_tpus, tpu
        if num_tpus() > 0:
            ctx_list.append(tpu())
    outs = []
    for ctx in ctx_list:
        ins = [nd.array(x, ctx=ctx) for x in inputs_np]
        out = f(*ins)
        outs.append(_to_np(out))
    for i in range(1, len(outs)):
        assert_almost_equal(outs[0], outs[i], rtol=rtol, atol=atol,
                            names=(str(ctx_list[0]), str(ctx_list[i])))
    return outs
