"""Optimizers (reference python/mxnet/optimizer/optimizer.py, P12).

API parity: ``Optimizer`` base with registry (``mx.optimizer.create('sgd')``),
``create_state``/``update``/``update_multi_precision``, lr/wd multipliers,
``Updater`` (the closure the reference ships to KVStore servers — here used by
kvstore local updaters), ``set_learning_rate``, lr_scheduler hook.

Each update call lowers to ONE fused XLA kernel via the optimizer ops
(mxnet_tpu/ops/optimizer_ops.py); per-step scalars are traced jit args so a
changing lr never recompiles.  Multi-precision: TPU master weights stay fp32
while bf16/fp16 weights are updated from them (mp_* parity).
"""

from __future__ import annotations

import numpy as _np

from .base import MXNetError
from . import config
from . import ndarray as nd
from .ndarray.ndarray import NDArray

__all__ = ["Optimizer", "SGD", "Adam", "AdamW", "NAG", "LARS", "RMSProp", "Ftrl",
           "Signum", "SignSGD", "LAMB", "AdaGrad", "AdaDelta", "create",
           "register", "Updater", "get_updater"]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    key = name.lower()
    if key not in _REGISTRY:
        raise MXNetError(f"unknown optimizer {name!r}; known {sorted(_REGISTRY)}")
    return _REGISTRY[key](**kwargs)


class Optimizer:
    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None, aggregate_num=0):
        self.aggregate_num = int(aggregate_num)
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient if clip_gradient is not None else -1.0
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = dict(param_dict or {})
        self.lr_mult = {}
        self.wd_mult = {}

    # -- lr/wd plumbing (reference Optimizer._get_lr/_get_wd) ---------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("cannot set lr directly when lr_scheduler is set")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self.num_update,
                              self._index_update_count[index])

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler \
            else self.lr
        param = self.param_dict.get(index)
        if param is not None:
            lr *= param.lr_mult
        else:
            lr *= self.lr_mult.get(index, 1.0)
            lr *= self.lr_mult.get(self.idx2name.get(index, ""), 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        param = self.param_dict.get(index)
        if param is not None:
            wd *= param.wd_mult
        else:
            wd *= self.wd_mult.get(index, 1.0)
            wd *= self.wd_mult.get(self.idx2name.get(index, ""), 1.0)
        return wd

    # -- to implement --------------------------------------------------------
    def create_state(self, index, weight):
        raise NotImplementedError

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    @staticmethod
    def _is_half(dtype):
        # float16 AND bfloat16 (the TPU-native half) get fp32 master copies
        dt = _np.dtype(dtype)
        return dt.kind == "f" and dt.itemsize == 2 or dt.name == "bfloat16"

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and self._is_half(weight.dtype):
            master = weight.astype(_np.float32)
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and self._is_half(weight.dtype):
            master, base_state = state
            self.update(index, master, grad.astype(_np.float32), base_state)
            weight._set_data(master.astype(weight.dtype)._data)
        else:
            self.update(index, weight, grad, state)

    def _common_kwargs(self, index):
        kw = {"lr": self._get_lr(index), "wd": self._get_wd(index),
              "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None and self.clip_gradient >= 0:
            kw["clip_gradient"] = self.clip_gradient
        return kw

    def update_multi(self, indices, weights, grads, states):
        """Fused N-param update: ONE donated jitted dispatch per dtype
        bucket through mxnet_tpu.optimizer_fusion (flat-buffer multi-
        tensor apply, bitwise identical to N update_multi_precision
        calls).  Optimizers the fusion layer does not reproduce — and
        every optimizer when ``MXNET_OPTIMIZER_FUSED=0`` — fall back to
        the per-param loop."""
        from . import optimizer_fusion as _fus
        if _fus.fusion_active(self):
            _fus.fused_update(self, indices, weights, grads, states)
            return
        for i, w, g, st in zip(indices, weights, grads, states):
            self.update_multi_precision(i, w, g, st)

    def __repr__(self):
        return f"{type(self).__name__}(lr={self.learning_rate})"


@register
class SGD(Optimizer):
    def __init__(self, momentum=0.0, lazy_update=False, **kwargs):
        # reference optimizer.py: SGD aggregates up to
        # MXNET_OPTIMIZER_AGGREGATION_SIZE params per fused kernel call
        # (default 4) — the multi_sgd_update family
        kwargs.setdefault("aggregate_num", config.get_int(
            "MXNET_OPTIMIZER_AGGREGATION_SIZE", 4))
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is None:
            nd.sgd_update(weight, grad, out=weight, **kw)
        else:
            nd.sgd_mom_update(weight, grad, state, out=[weight, state],
                              momentum=self.momentum, **kw)

    def update_multi(self, indices, weights, grads, states):
        """Fused N-param update — ONE dispatch via the flat-buffer donated
        executables (optimizer_fusion) when MXNET_OPTIMIZER_FUSED is on,
        else the multi_sgd_update / multi_mp_sgd_* registry ops
        (reference optimizer_op.cc multi-tensor kernels).  Numerics
        identical to N update() calls either way."""
        from . import optimizer_fusion as _fus
        if _fus.fusion_active(self):
            # exact-SGD only: subclasses (and a disabled/zero-bucket knob)
            # keep the legacy multi_sgd kernels below
            _fus.fused_update(self, indices, weights, grads, states)
            return
        for i in indices:
            self._update_count(i)
        # lr/wd vectors must live WITH the weights (a cpu-ctx vector next
        # to tpu-ctx params fails the jitted dispatch's device check)
        wctx = weights[0].ctx
        lrs = nd.array(_np.array([self._get_lr(i) for i in indices],
                                 _np.float32), ctx=wctx)
        wds = nd.array(_np.array([self._get_wd(i) for i in indices],
                                 _np.float32), ctx=wctx)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        mp = [self.multi_precision and self._is_half(w.dtype)
              for w in weights]
        if any(mp):
            assert all(mp), "update_multi groups must not mix precisions"
            if self.momentum == 0.0:
                ins, outs = [], []
                for w, g, st in zip(weights, grads, states):
                    ins += [w, g, st[0]]
                    outs += [w, st[0]]
                nd.multi_mp_sgd_update(
                    *ins, lrs, wds, out=outs,
                    rescale_grad=self.rescale_grad, clip_gradient=clip,
                    num_weights=len(indices))
            else:
                ins, outs = [], []
                for w, g, st in zip(weights, grads, states):
                    ins += [w, g, st[1], st[0]]
                    outs += [w, st[1], st[0]]
                nd.multi_mp_sgd_mom_update(
                    *ins, lrs, wds, out=outs, momentum=self.momentum,
                    rescale_grad=self.rescale_grad, clip_gradient=clip,
                    num_weights=len(indices))
            return
        if self.momentum == 0.0:
            ins = [x for w, g in zip(weights, grads) for x in (w, g)]
            nd.multi_sgd_update(
                *ins, lrs, wds, out=list(weights),
                rescale_grad=self.rescale_grad, clip_gradient=clip,
                num_weights=len(indices))
        else:
            ins, outs = [], []
            for w, g, m in zip(weights, grads, states):
                ins += [w, g, m]
                outs += [w, m]
            nd.multi_sgd_mom_update(
                *ins, lrs, wds, out=outs, momentum=self.momentum,
                rescale_grad=self.rescale_grad, clip_gradient=clip,
                num_weights=len(indices))


@register
class LARS(Optimizer):
    """Layer-wise Adaptive Rate Scaling (reference optimizer.py :: LARS
    over optimizer_op.cc lars_*/multi_lars — large-batch SGD, You et al.
    2017).  Per-layer lr scales by trust = ||w|| / (||g|| + wd*||w||+eps)
    via the fused ``lars_update`` op."""

    def __init__(self, momentum=0.9, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx)

    def _skip_trust(self, index):
        # reference LARS excludes bias/gamma/beta from layer adaptation.
        # Gluon Trainer populates param_dict (not idx2name), so consult
        # the Parameter's name there too
        name = self.idx2name.get(index, "")
        if not name:
            name = getattr(self.param_dict.get(index), "name", "") or ""
        return name.endswith(("bias", "gamma", "beta"))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if self._skip_trust(index):
            # trust ratio forced to 1: plain momentum SGD
            nd.sgd_mom_update(weight, grad, state, out=[weight, state],
                              momentum=self.momentum, **kw)
        else:
            nd.lars_update(weight, grad, state, out=[weight, state],
                           momentum=self.momentum, eta=self.eta,
                           epsilon=self.epsilon, **kw)


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is None:
            nd.sgd_update(weight, grad, out=weight, **kw)
        else:
            nd.nag_mom_update(weight, grad, state, out=[weight, state],
                              momentum=self.momentum, **kw)


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common_kwargs(index)
        # bias correction folded into lr (reference adam_update does this)
        # operator-only math: t may be a traced scalar inside the fused
        # SPMD train step (mxnet_tpu.parallel.TrainStep), where np ufuncs
        # would force concretization
        kw["lr"] *= (1. - self.beta2 ** t) ** 0.5 / (1. - self.beta1 ** t)
        mean, var = state
        nd.adam_update(weight, grad, mean, var, out=[weight, mean, var],
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, **kw)


@register
class AdamW(Optimizer):
    """Decoupled weight decay (reference contrib adamw_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, eta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon, self.eta = beta1, beta2, epsilon, eta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        mean, var = state
        nd.adamw_update(weight, grad, mean, var, out=[weight, mean, var],
                        beta1=self.beta1, beta2=self.beta2,
                        epsilon=self.epsilon, eta=self.eta, **kw)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights if clip_weights is not None else -1.0

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx)
        if self.centered:
            return (z(), z(), z())
        return (z(),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if self.centered:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta,
                                  out=[weight, n, g, delta],
                                  gamma1=self.gamma1, gamma2=self.gamma2,
                                  epsilon=self.epsilon,
                                  clip_weights=self.clip_weights, **kw)
        else:
            (n,) = state
            nd.rmsprop_update(weight, grad, n, out=[weight, n],
                              gamma1=self.gamma1, epsilon=self.epsilon,
                              clip_weights=self.clip_weights, **kw)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        z, n = state
        nd.ftrl_update(weight, grad, z, n, out=[weight, z, n],
                       lamda1=self.lamda1, beta=self.beta, **kw)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is None:
            nd.signsgd_update(weight, grad, out=weight, **kw)
        else:
            nd.signum_update(weight, grad, state, out=[weight, state],
                             momentum=self.momentum, wd_lh=self.wd_lh, **kw)


SignSGD = Signum


@register
class LAMB(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound = lower_bound if lower_bound is not None else -1.0
        self.upper_bound = upper_bound if upper_bound is not None else -1.0
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common_kwargs(index)
        mean, var = state
        nd.lamb_full_update(weight, grad, mean, var,
                            out=[weight, mean, var],
                            beta1=self.beta1, beta2=self.beta2,
                            epsilon=self.epsilon, t=t,
                            bias_correction=self.bias_correction,
                            lower_bound=self.lower_bound,
                            upper_bound=self.upper_bound, **kw)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, learning_rate=0.01, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        nd.adagrad_update(weight, grad, state, out=[weight, state],
                          epsilon=self.float_stable_eps, **kw)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        acc_g, acc_d = state
        nd.adadelta_update(weight, grad, acc_g, acc_d,
                           out=[weight, acc_g, acc_d],
                           rho=self.rho, epsilon=self.epsilon,
                           wd=self._get_wd(index),
                           rescale_grad=self.rescale_grad,
                           clip_gradient=self.clip_gradient)


class Updater:
    """The state-managing closure (reference Optimizer.get_updater) — the
    object the reference serializes to KVStore servers; here used by local
    kvstore updaters and Trainer."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        self.optimizer.update_multi_precision(
            index, weight, grad, self._ensure_state(index, weight))

    def _ensure_state(self, index, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        elif not self.states_synced.get(index, True):
            # states deserialized by set_states land on the default ctx;
            # move them to the weight's ctx on first use (reference
            # Updater.sync_state_context)
            self.states[index] = _state_to_ctx(self.states[index],
                                               weight.ctx)
            self.states_synced[index] = True
        return self.states[index]

    def call_multi(self, indices, grads, weights):
        """Fused multi-param step (reference updater aggregation over the
        multi_sgd kernels): one optimizer.update_multi per group."""
        states = [self._ensure_state(i, w)
                  for i, w in zip(indices, weights)]
        self.optimizer.update_multi(indices, weights, grads, states)

    def call_fused(self, indices, grads, weights, flat_grad=None,
                   shapes=None, sizes=None):
        """Flat-buffer fused step (optimizer_fusion): per-param grads plan
        their own dtype buckets; a ``flat_grad`` buffer (one reduced
        bucket straight off the kvstore wire, pushpull_flat) feeds the
        donated update directly with the provided bucket layout."""
        from . import optimizer_fusion as _fus
        states = [self._ensure_state(i, w)
                  for i, w in zip(indices, weights)]
        if flat_grad is not None:
            _fus.fused_update_flat(self.optimizer, indices, weights,
                                   states, shapes, sizes, flat_grad)
        else:
            _fus.fused_update(self.optimizer, indices, weights, grads,
                              states)

    def get_states(self, dump_optimizer=False):  # noqa: ARG002
        import pickle
        flat = {}
        for k, st in self.states.items():
            flat[k] = _state_to_numpy(st)
        # update counts ride along: without them a resumed Adam/LAMB run
        # restarts bias correction at t=0 and the loss curve diverges
        payload = {"states": flat,
                   "index_update_count": dict(
                       self.optimizer._index_update_count),
                   "num_update": self.optimizer.num_update}
        return pickle.dumps(payload)

    def set_states(self, states):
        import pickle
        flat = pickle.loads(states)
        if isinstance(flat, dict) and "states" in flat \
                and "num_update" in flat:
            self.optimizer._index_update_count = dict(
                flat["index_update_count"])
            self.optimizer.num_update = flat["num_update"]
            flat = flat["states"]
        self.states = {k: _state_from_numpy(v) for k, v in flat.items()}
        self.states_synced = {k: False for k in self.states}


def _state_to_numpy(st):
    if st is None:
        return None
    if isinstance(st, (list, tuple)):
        return type(st)(_state_to_numpy(s) for s in st)
    return st.asnumpy()


def _state_from_numpy(st):
    if st is None:
        return None
    if isinstance(st, (list, tuple)):
        return type(st)(_state_from_numpy(s) for s in st)
    return nd.array(st)


def _state_to_ctx(st, ctx):
    if st is None:
        return None
    if isinstance(st, (list, tuple)):
        return type(st)(_state_to_ctx(s, ctx) for s in st)
    return st.as_in_context(ctx)


def get_updater(optimizer):
    return Updater(optimizer)
