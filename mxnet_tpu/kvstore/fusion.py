"""Gradient bucketing — flat-buffer fusion for the dense kvstore path.

The per-key pushpull loop pays one host dispatch chain (reduce + store +
per-replica copy + telemetry) per parameter; at BERT-base scale that is
~400 host round-trips per step of pure per-key overhead.  Every serious
data-parallel stack fuses: MXNet's NCCL kvstore batches keys up to
``MXNET_KVSTORE_BIGARRAY_BOUND``, PyTorch DDP and Horovod concat grads
into ~25 MB flat buckets and run ONE collective per bucket.

This module is that layer for the TPU rebuild:

- ``GradBucketer.plan(signature)`` groups same-``(dtype, n_replica)``
  dense gradients, in key order, into size-bounded buckets
  (``MXNET_KVSTORE_BUCKET_MB``, default 25; one oversized grad gets its
  own bucket).
- Per bucket, ONE jitted executable reduces every key's replicas in a
  single dispatch.  Two strategies, both elementwise identical to
  ``KVStoreLocal._reduce`` (stack + axis-sum per element, an O(log R)
  tree): in-process, ``reduce_bucket`` sums replicas per key with no
  data movement beyond the adds; across processes, ``reduce_flat``
  flatten-concats each replica into one flat buffer so the dist store
  runs ONE psum per bucket on the wire, and ``unflatten`` is one jitted
  split+reshape back into per-key views.  A single-replica in-process
  bucket is an identity reduction and dispatches nothing at all.
- Plans and executables are cached per bucket signature, so steady-state
  steps are pure cache hits: ``builds`` counts executable constructions
  and stays flat after step one (the retrace-count invariant
  tests/test_kvstore_fusion.py asserts).

Bit-identity contract: summing the concatenation and then splitting
performs exactly the same per-element addition tree as summing each key
separately, so the fused path is bit-identical to the per-key path and
callers may switch freely.  Sparse values, compressed keys, and
update-on-kvstore keys never enter a bucket — ``KVStoreLocal``
falls back to the per-key loop for those.
"""

from __future__ import annotations

import numpy as _np

from .. import telemetry as _tel
from ..telemetry import costmodel as _costmodel

__all__ = ["GradBucketer", "bucket_bytes_from_env", "tree_sum",
           "DEFAULT_BUCKET_MB"]

DEFAULT_BUCKET_MB = 25.0

# fused-path visibility (ISSUE 2 tentpole): how many keys ride fused vs
# fall back, how many buckets (= device dispatches) they collapse into,
# and the per-bucket host latency distribution
_M_FUSED_PUSHPULLS = _tel.counter(
    "mxnet_kvstore_fused_pushpulls_total",
    "Fused pushpull_list calls taking the bucketed path.")
_M_FUSED_BUCKETS = _tel.counter(
    "mxnet_kvstore_fused_buckets_total",
    "Gradient buckets dispatched (one fused reduce each).")
_M_FUSED_BYTES = _tel.counter(
    "mxnet_kvstore_fused_bytes_total",
    "Bytes entering fused bucket reductions (all replicas).")
_M_FUSED_KEYS = _tel.counter(
    "mxnet_kvstore_fused_keys_total",
    "Keys reduced through the fused bucket path.")
_M_FALLBACK_KEYS = _tel.counter(
    "mxnet_kvstore_fused_fallback_keys_total",
    "pushpull_list keys that fell back to the per-key path "
    "(sparse / compressed / update-on-kvstore / uninitialized).")
_M_BUCKET_SECONDS = _tel.histogram(
    "mxnet_kvstore_fused_bucket_seconds",
    "Host-side latency per fused bucket (flatten+reduce+scatter dispatch).")
_M_BUCKET_ERRORS = _tel.counter(
    "mxnet_kvstore_fused_bucket_errors_total",
    "Fused buckets whose executable FAILED and were replayed through the "
    "per-key path (ISSUE 3 graceful degradation — distinct from the "
    "planned fallback rules above, which never enter a bucket).")


def tree_sum(arrays):
    """Pairwise-tree sum of a list of arrays: O(log n) depth, and — unlike
    an axis reduction over a stacked array, whose accumulation order XLA
    may re-vectorize differently per fusion context — a FIXED association
    of IEEE adds.  Every reduction in this subsystem (per-key
    ``KVStoreLocal._reduce`` and both fused bucket executables) goes
    through this one function, which is what makes fused and per-key
    results bit-identical at any replica count."""
    arrs = list(arrays)
    while len(arrs) > 1:
        nxt = [arrs[i] + arrs[i + 1] for i in range(0, len(arrs) - 1, 2)]
        if len(arrs) % 2:
            nxt.append(arrs[-1])
        arrs = nxt
    return arrs[0]


def bucket_bytes_from_env():
    """MXNET_KVSTORE_BUCKET_MB → bytes; <= 0 disables fusion."""
    from .. import config
    return int(config.get_float("MXNET_KVSTORE_BUCKET_MB",
                                DEFAULT_BUCKET_MB) * (1 << 20))


class _Bucket:
    """One fused group: positions into the caller's key list plus the
    frozen (shapes, sizes, dtype, n_rep) layout the executables key on."""

    __slots__ = ("positions", "shapes", "sizes", "dtype", "n_rep", "nbytes")

    def __init__(self, dtype, n_rep):
        self.positions = []
        self.shapes = []
        self.sizes = []
        self.dtype = dtype
        self.n_rep = n_rep
        self.nbytes = 0

    def _freeze(self):
        self.positions = tuple(self.positions)
        self.shapes = tuple(self.shapes)
        self.sizes = tuple(self.sizes)

    @property
    def exec_key(self):
        return (self.shapes, self.dtype, self.n_rep)

    def __repr__(self):
        return (f"<_Bucket keys={len(self.positions)} dtype={self.dtype} "
                f"n_rep={self.n_rep} bytes={self.nbytes}>")


class GradBucketer:
    """Plans size-bounded same-dtype buckets and owns their cached jitted
    flatten-reduce / unflatten executables.

    ``builds`` counts executable constructions — a steady-state training
    loop must not grow it after the first step (retrace invariant).
    """

    def __init__(self, bucket_bytes=None):
        if bucket_bytes is None:
            bucket_bytes = bucket_bytes_from_env()
        self.bucket_bytes = int(bucket_bytes)
        self.builds = 0
        self._plan_cache = {}
        self._reduce_cache = {}
        self._reduce_keys_cache = {}
        self._unflat_cache = {}

    # -- planning ------------------------------------------------------------
    def plan(self, signature):
        """signature: tuple of (shape, dtype_str, n_rep) per key →
        cached list of _Bucket (positions index into the signature)."""
        buckets = self._plan_cache.get(signature)
        if buckets is None:
            buckets = self._build_plan(signature)
            self._plan_cache[signature] = buckets
        return buckets

    def _build_plan(self, signature):
        buckets = []
        open_by_group = {}  # (dtype, n_rep) -> still-filling bucket
        for pos, (shape, dtype, n_rep) in enumerate(signature):
            size = 1
            for d in shape:
                size *= int(d)
            nbytes = size * _np.dtype(dtype).itemsize
            group = (dtype, n_rep)
            cur = open_by_group.get(group)
            if cur is not None and cur.nbytes + nbytes > self.bucket_bytes:
                cur = None  # close it; a fresh bucket takes this key
            if cur is None:
                cur = _Bucket(dtype, n_rep)
                open_by_group[group] = cur
                buckets.append(cur)
            cur.positions.append(pos)
            cur.shapes.append(tuple(shape))
            cur.sizes.append(size)
            cur.nbytes += nbytes
        for b in buckets:
            b._freeze()
        return buckets

    # -- executables ---------------------------------------------------------
    def reduce_flat(self, bucket, arrays):
        """arrays: replica-major flat list (replica r's grads for every key,
        then replica r+1's ...) → ONE flat buffer holding the replica sum."""
        fn = self._reduce_cache.get(bucket.exec_key)
        if fn is None:
            fn = self._build_reduce(len(bucket.shapes), bucket.n_rep)
            self._reduce_cache[bucket.exec_key] = fn
            self.builds += 1
        return fn(*arrays)

    def reduce_bucket(self, bucket, arrays):
        """arrays: replica-major flat list → tuple of per-key replica sums,
        ONE dispatch for the whole bucket and no concat data movement (the
        in-process strategy; the wire strategy is reduce_flat+unflatten)."""
        key = (len(bucket.shapes), bucket.dtype, bucket.n_rep)
        fn = self._reduce_keys_cache.get(key)
        if fn is None:
            fn = self._build_reduce_keys(len(bucket.shapes), bucket.n_rep)
            self._reduce_keys_cache[key] = fn
            self.builds += 1
        return fn(*arrays)

    def unflatten(self, bucket, flat):
        """Flat reduced buffer → tuple of per-key arrays in bucket layout."""
        key = (bucket.shapes, bucket.dtype)
        fn = self._unflat_cache.get(key)
        if fn is None:
            fn = self._build_unflatten(bucket.shapes, bucket.sizes)
            self._unflat_cache[key] = fn
            self.builds += 1
        return fn(flat)

    @staticmethod
    def _build_reduce(n_keys, n_rep):
        import jax
        import jax.numpy as jnp

        def fuse(*arrs):
            flats = []
            for r in range(n_rep):
                chunk = arrs[r * n_keys:(r + 1) * n_keys]
                flats.append(jnp.concatenate([jnp.ravel(a) for a in chunk])
                             if n_keys > 1 else jnp.ravel(chunk[0]))
            return tree_sum(flats)

        return _costmodel.wrap_jit(jax.jit(fuse), "kvstore.fusion.reduce")

    @staticmethod
    def _build_reduce_keys(n_keys, n_rep):
        import jax

        def fuse(*arrs):
            # the same fixed-association tree per key as _reduce
            return tuple(
                tree_sum([arrs[r * n_keys + i] for r in range(n_rep)])
                for i in range(n_keys))

        return _costmodel.wrap_jit(jax.jit(fuse), "kvstore.fusion.reduce")

    @staticmethod
    def _build_unflatten(shapes, sizes):
        import jax

        def unflat(flat):
            out, off = [], 0
            for shape, size in zip(shapes, sizes):
                out.append(flat[off:off + size].reshape(shape))
                off += size
            return tuple(out)

        return _costmodel.wrap_jit(jax.jit(unflat),
                                   "kvstore.fusion.unflatten")


# -- telemetry hooks (callers gate on tracer._ENABLED) -----------------------

def record_bucket(bucket, dt_ns):
    _M_FUSED_BUCKETS.inc()
    _M_FUSED_KEYS.inc(len(bucket.positions))
    _M_FUSED_BYTES.inc(bucket.nbytes * bucket.n_rep)
    _M_BUCKET_SECONDS.observe(dt_ns / 1e9)


def record_pushpull():
    _M_FUSED_PUSHPULLS.inc()


def record_fallback(n_keys):
    if n_keys:
        _M_FALLBACK_KEYS.inc(n_keys)


def record_bucket_error(n_keys):
    """One fused bucket errored at execution time and degraded per-key
    (unconditional: failures are rare and must never be invisible)."""
    _M_BUCKET_ERRORS.inc()
    _M_FALLBACK_KEYS.inc(n_keys)
