"""Host-resident KV service for row-sparse parameters — the surviving
parameter-server role (SURVEY §5.8/§7.1: "PS semantics retained ONLY for
sparse embeddings").

Reference: ``src/kvstore/kvstore_dist_server.h`` (N14: the server stores
the table, aggregates sparse grads, runs the optimizer server-side) +
``kvstore_dist.h :: PullRowSparse`` (N13) + the lazy sparse update
semantics of ``src/operator/optimizer_op.cc`` (row_sparse sgd/adagrad:
ONLY touched rows advance).

TPU-native shape: embedding tables too big for HBM stay in host RAM as
numpy arrays; the training step pulls just the rows a batch touches
(``row_sparse_pull``) onto the device, and pushes row-sparse grads back,
where the SAME python optimizer the device uses runs on cpu-context
NDArrays of the touched rows — the reference's server-side-optimizer
contract without server processes.  Optimizer state lives host-side as
full-table numpy arrays (what the reference server holds), gathered and
scattered by vectorized fancy indexing; rows are state-initialized on
first touch via ``create_state_multi_precision`` on their current values
(so e.g. fp32 master-weight leaves start at the row values, momenta at
their true initial state — never blind zeros).

Multi-host note: each worker process owns the full service for its own
tables in this build (BASELINE config 4 is single-host); sharding rows
across hosts would run one service per host behind a row->host hash over
the existing jax.distributed rendezvous.
"""

from __future__ import annotations

import threading

import numpy as _np

from ..base import MXNetError

__all__ = ["SparsePS"]


class _Table:
    __slots__ = ("value", "lock", "state_leaves", "state_inited")

    def __init__(self, value):
        self.value = value          # numpy (rows, *cols) — host RAM
        self.lock = threading.Lock()
        # full-table optimizer state: list of dense numpy arrays (one per
        # state leaf, row-major like value) + per-row inited mask; tree
        # structure is recorded in SparsePS._state_tree
        self.state_leaves = None
        self.state_inited = None


class SparsePS:
    """The host KV service: init/push/row_sparse_pull + server-side opt."""

    def __init__(self):
        self._tables = {}
        self._optimizer = None
        self._updaters = {}
        self._state_tree = {}  # key -> structure template (see _tree_of)

    # -- registration -------------------------------------------------------
    def init(self, key, value):
        if key in self._tables:
            raise MXNetError(f"sparse key {key!r} already initialized")
        from ..ndarray import sparse as sp
        if isinstance(value, sp.RowSparseNDArray):
            dense = value.tostype("default").asnumpy()
        else:
            dense = value.asnumpy()
        self._tables[key] = _Table(_np.array(dense, copy=True))

    def keys(self):
        return sorted(self._tables)

    def shape(self, key):
        return self._tables[key].value.shape

    def set_optimizer(self, optimizer):
        """Server-side optimizer (reference kvstore.set_optimizer →
        server runs the updater).  Switching optimizers resets ALL
        per-row state (stale momenta must not feed the new update rule)."""
        self._optimizer = optimizer
        self._updaters = {}
        self._state_tree = {}
        for tbl in self._tables.values():
            with tbl.lock:
                tbl.state_leaves = None
                tbl.state_inited = None

    # -- traffic ------------------------------------------------------------
    def push(self, key, grad):
        """Apply a row-sparse gradient to the table, lazily (touched rows
        only — reference row_sparse sgd_update semantics)."""
        from .. import optimizer as opt
        from .. import ndarray as nd
        from ..ndarray import sparse as sp
        tbl = self._tables.get(key)
        if tbl is None:
            raise MXNetError(f"sparse key {key!r} not initialized")
        if isinstance(grad, sp.RowSparseNDArray):
            rows = _np.asarray(grad.indices.asnumpy(), _np.int64)
            vals = _np.asarray(grad.data.asnumpy())
        else:
            rows = _np.arange(tbl.value.shape[0])
            vals = grad.asnumpy()
        if rows.size == 0:
            return
        # aggregate duplicate rows (reference merge buffer)
        uniq, inv = _np.unique(rows, return_inverse=True)
        merged = _np.zeros((len(uniq),) + vals.shape[1:], vals.dtype)
        _np.add.at(merged, inv, vals)
        with tbl.lock:
            if self._optimizer is None:
                tbl.value[uniq] += merged  # raw accumulate (no updater)
                return
            upd = self._updaters.get(key)
            if upd is None:
                upd = opt.get_updater(self._optimizer)
                self._updaters[key] = upd
            w = nd.array(tbl.value[uniq])
            g = nd.array(merged)
            self._ensure_states(tbl, key, uniq, w)
            upd.states[key] = self._gather_states(tbl, key, uniq)
            upd(key, g, w)
            self._scatter_states(tbl, key, uniq, upd.states[key])
            tbl.value[uniq] = w.asnumpy()

    # -- per-row optimizer state (dense host arrays, vectorized IO) ---------
    def _ensure_states(self, tbl, key, rows, w_block):
        """Allocate dense state arrays once; state-init first-touch rows by
        running create_state on their CURRENT values."""
        from .. import ndarray as nd
        if key not in self._state_tree:
            proto = self._optimizer.create_state_multi_precision(
                key, w_block[:1])
            self._state_tree[key] = _tree_of(proto)
            leaves = _leaves_of(proto)
            n_rows = tbl.value.shape[0]
            tbl.state_leaves = [
                _np.zeros((n_rows,) + tuple(lf.shape[1:]),
                          _np.dtype(lf.dtype)) for lf in leaves]
            tbl.state_inited = _np.zeros(n_rows, bool)
        fresh = rows[~tbl.state_inited[rows]]
        if fresh.size:
            init_state = self._optimizer.create_state_multi_precision(
                key, nd.array(tbl.value[fresh]))
            for dst, lf in zip(tbl.state_leaves, _leaves_of(init_state)):
                dst[fresh] = lf.asnumpy()
            tbl.state_inited[fresh] = True

    def _gather_states(self, tbl, key, rows):
        from .. import ndarray as nd
        blocks = [nd.array(leaf[rows]) for leaf in tbl.state_leaves]
        return _tree_build(self._state_tree[key], iter(blocks))

    def _scatter_states(self, tbl, key, rows, states):
        for leaf_arr, nd_leaf in zip(tbl.state_leaves, _leaves_of(states)):
            leaf_arr[rows] = nd_leaf.asnumpy()

    def row_sparse_pull(self, key, row_ids):
        """Gather the requested rows → RowSparseNDArray on device."""
        from .. import ndarray as nd
        from ..ndarray import sparse as sp
        tbl = self._tables.get(key)
        if tbl is None:
            raise MXNetError(f"sparse key {key!r} not initialized")
        rows = _np.unique(_np.asarray(row_ids.asnumpy(), _np.int64))
        with tbl.lock:
            block = tbl.value[rows]
        return sp.RowSparseNDArray(
            nd.array(block), nd.array(rows), tbl.value.shape)

    def pull_dense(self, key):
        from .. import ndarray as nd
        tbl = self._tables[key]
        with tbl.lock:
            return nd.array(tbl.value.copy())


# -- state-tree helpers ------------------------------------------------------
# a state is None | NDArray | (nested) tuple/list of those; leaves are
# enumerated left-to-right so dense arrays and trees stay aligned

def _leaves_of(state):
    if state is None:
        return []
    if isinstance(state, (list, tuple)):
        out = []
        for s in state:
            out.extend(_leaves_of(s))
        return out
    return [state]


def _tree_of(state):
    """Structure template: None | 'leaf' | (type, [templates])."""
    if state is None:
        return None
    if isinstance(state, (list, tuple)):
        return (type(state), [_tree_of(s) for s in state])
    return "leaf"


def _tree_build(tmpl, leaf_iter):
    if tmpl is None:
        return None
    if tmpl == "leaf":
        return next(leaf_iter)
    t, subs = tmpl
    return t(_tree_build(s, leaf_iter) for s in subs)
