"""Host-resident KV service for row-sparse parameters — the surviving
parameter-server role (SURVEY §5.8/§7.1: "PS semantics retained ONLY for
sparse embeddings").

Reference: ``src/kvstore/kvstore_dist_server.h`` (N14: the server stores
the table, aggregates sparse grads, runs the optimizer server-side) +
``kvstore_dist.h :: PullRowSparse`` (N13) + the lazy sparse update
semantics of ``src/operator/optimizer_op.cc`` (row_sparse sgd/adagrad:
ONLY touched rows advance).

TPU-native shape: embedding tables too big for HBM stay in host RAM as
numpy arrays; the training step pulls just the rows a batch touches
(``row_sparse_pull``) onto the device, and pushes row-sparse grads back,
where the SAME python optimizer the device uses runs on cpu-context
NDArrays of the touched rows — the reference's server-side-optimizer
contract without server processes.  Optimizer state lives host-side as
full-table numpy arrays (what the reference server holds), gathered and
scattered by vectorized fancy indexing; rows are state-initialized on
first touch via ``create_state_multi_precision`` on their current values
(so e.g. fp32 master-weight leaves start at the row values, momenta at
their true initial state — never blind zeros).

Multi-host note: each worker process owns the full service for its own
tables in this build (BASELINE config 4 is single-host); sharding rows
across hosts would run one service per host behind a row->host hash over
the existing jax.distributed rendezvous.
"""

from __future__ import annotations

import threading

import numpy as _np

from ..analysis.runtime import tracked as _tracked
from ..base import MXNetError

__all__ = ["SparsePS"]


class _Table:
    __slots__ = ("value", "lock", "state_leaves", "state_inited")

    def __init__(self, value):
        self.value = value          # numpy (rows, *cols) — host RAM
        self.lock = _tracked(threading.Lock(), "SparsePS._Table.lock")
        # full-table optimizer state: list of dense numpy arrays (one per
        # state leaf, row-major like value) + per-row inited mask; tree
        # structure is recorded in SparsePS._state_tree
        self.state_leaves = None
        self.state_inited = None


class SparsePS:
    """The host KV service: init/push/row_sparse_pull + server-side opt."""

    def __init__(self):
        self._tables = {}
        self._optimizer = None
        self._updaters = {}
        self._state_tree = {}  # key -> structure template (see _tree_of)
        # service-wide lock guarding the shared optimizer/updater/state
        # maps (per-table data rides each _Table's own lock).  Acquisition
        # order is ALWAYS self._lock -> tbl.lock; found by graftcheck GC04:
        # set_optimizer used to reset these maps lock-free while push
        # installed updaters under a table lock, so a concurrent push
        # could resurrect a stale-optimizer updater after the reset.
        # _gen bumps on every optimizer swap: push snapshots (gen,
        # optimizer, updater) under _lock, runs the heavy per-table update
        # under tbl.lock ONLY (pushes to different tables stay concurrent),
        # and restarts if the generation moved in between — a stale
        # updater can never write state past a reset.
        self._lock = _tracked(threading.Lock(), "SparsePS._lock")
        self._gen = 0

    # -- registration -------------------------------------------------------
    def init(self, key, value):
        if key in self._tables:
            raise MXNetError(f"sparse key {key!r} already initialized")
        from ..ndarray import sparse as sp
        if isinstance(value, sp.RowSparseNDArray):
            dense = value.tostype("default").asnumpy()
        else:
            dense = value.asnumpy()
        self._tables[key] = _Table(_np.array(dense, copy=True))

    def keys(self):
        return sorted(self._tables)

    def shape(self, key):
        return self._tables[key].value.shape

    def set_optimizer(self, optimizer):
        """Server-side optimizer (reference kvstore.set_optimizer →
        server runs the updater).  Switching optimizers resets ALL
        per-row state (stale momenta must not feed the new update rule)."""
        with self._lock:
            self._gen += 1
            self._optimizer = optimizer
            self._updaters = {}
            self._state_tree = {}
            for tbl in self._tables.values():
                with tbl.lock:
                    tbl.state_leaves = None
                    tbl.state_inited = None
            # the per-table loop just synchronized with every in-flight
            # old-generation push (each holds its tbl.lock until done) —
            # any _state_tree entry such a push wrote between our clear
            # above and its table's clear is wiped here, totally
            self._state_tree = {}

    # -- traffic ------------------------------------------------------------
    def push(self, key, grad):
        """Apply a row-sparse gradient to the table, lazily (touched rows
        only — reference row_sparse sgd_update semantics)."""
        from .. import optimizer as opt
        from .. import ndarray as nd
        from ..ndarray import sparse as sp
        tbl = self._tables.get(key)
        if tbl is None:
            raise MXNetError(f"sparse key {key!r} not initialized")
        if isinstance(grad, sp.RowSparseNDArray):
            rows = _np.asarray(grad.indices.asnumpy(), _np.int64)
            vals = _np.asarray(grad.data.asnumpy())
        else:
            rows = _np.arange(tbl.value.shape[0])
            vals = grad.asnumpy()
        if rows.size == 0:
            return
        # aggregate duplicate rows (reference merge buffer)
        uniq, inv = _np.unique(rows, return_inverse=True)
        merged = _np.zeros((len(uniq),) + vals.shape[1:], vals.dtype)
        _np.add.at(merged, inv, vals)
        while True:
            with self._lock:
                gen = self._gen
                optimizer = self._optimizer
                upd = self._updaters.get(key)
                if optimizer is not None and upd is None:
                    upd = opt.get_updater(optimizer)
                    self._updaters[key] = upd
            with tbl.lock:
                if gen != self._gen:
                    continue  # optimizer swapped between the locks —
                    # re-snapshot so no stale updater writes fresh state
                if optimizer is None:
                    tbl.value[uniq] += merged  # raw accumulate (no updater)
                    return
                w = nd.array(tbl.value[uniq])
                g = nd.array(merged)
                self._ensure_states(tbl, key, uniq, w, optimizer)
                upd.states[key] = self._gather_states(tbl, key, uniq)
                upd(key, g, w)
                self._scatter_states(tbl, key, uniq, upd.states[key])
                tbl.value[uniq] = w.asnumpy()
                return

    # -- per-row optimizer state (dense host arrays, vectorized IO) ---------
    def _ensure_states(self, tbl, key, rows, w_block, optimizer):
        """Allocate dense state arrays once; state-init first-touch rows by
        running create_state on their CURRENT values.  ``optimizer`` is the
        caller's generation snapshot — reading self._optimizer here could
        see a mid-push swap."""
        from .. import ndarray as nd
        if key not in self._state_tree:
            proto = optimizer.create_state_multi_precision(
                key, w_block[:1])
            # graftcheck: ignore[GC04] — caller (push) holds tbl.lock and
            # the generation check; set_optimizer re-clears this map after
            # synchronizing on every table lock, so a stale write here
            # cannot survive an optimizer swap
            self._state_tree[key] = _tree_of(proto)
            leaves = _leaves_of(proto)
            n_rows = tbl.value.shape[0]
            tbl.state_leaves = [
                _np.zeros((n_rows,) + tuple(lf.shape[1:]),
                          _np.dtype(lf.dtype)) for lf in leaves]
            tbl.state_inited = _np.zeros(n_rows, bool)
        fresh = rows[~tbl.state_inited[rows]]
        if fresh.size:
            init_state = optimizer.create_state_multi_precision(
                key, nd.array(tbl.value[fresh]))
            for dst, lf in zip(tbl.state_leaves, _leaves_of(init_state)):
                dst[fresh] = lf.asnumpy()
            tbl.state_inited[fresh] = True

    def _gather_states(self, tbl, key, rows):
        from .. import ndarray as nd
        blocks = [nd.array(leaf[rows]) for leaf in tbl.state_leaves]
        return _tree_build(self._state_tree[key], iter(blocks))

    def _scatter_states(self, tbl, key, rows, states):
        for leaf_arr, nd_leaf in zip(tbl.state_leaves, _leaves_of(states)):
            leaf_arr[rows] = nd_leaf.asnumpy()

    def row_sparse_pull(self, key, row_ids):
        """Gather the requested rows → RowSparseNDArray on device."""
        from .. import ndarray as nd
        from ..ndarray import sparse as sp
        tbl = self._tables.get(key)
        if tbl is None:
            raise MXNetError(f"sparse key {key!r} not initialized")
        rows = _np.unique(_np.asarray(row_ids.asnumpy(), _np.int64))
        with tbl.lock:
            block = tbl.value[rows]
        return sp.RowSparseNDArray(
            nd.array(block), nd.array(rows), tbl.value.shape)

    def pull_dense(self, key):
        from .. import ndarray as nd
        tbl = self._tables[key]
        with tbl.lock:
            return nd.array(tbl.value.copy())


# -- state-tree helpers ------------------------------------------------------
# a state is None | NDArray | (nested) tuple/list of those; leaves are
# enumerated left-to-right so dense arrays and trees stay aligned

def _leaves_of(state):
    if state is None:
        return []
    if isinstance(state, (list, tuple)):
        out = []
        for s in state:
            out.extend(_leaves_of(s))
        return out
    return [state]


def _tree_of(state):
    """Structure template: None | 'leaf' | (type, [templates])."""
    if state is None:
        return None
    if isinstance(state, (list, tuple)):
        return (type(state), [_tree_of(s) for s in state])
    return "leaf"


def _tree_build(tmpl, leaf_iter):
    if tmpl is None:
        return None
    if tmpl == "leaf":
        return next(leaf_iter)
    t, subs = tmpl
    return t(_tree_build(s, leaf_iter) for s in subs)
