"""Host-resident sharded KV service for row-sparse parameters — the
surviving parameter-server role (SURVEY §5.8/§7.1: "PS semantics retained
ONLY for sparse embeddings").

Reference: ``src/kvstore/kvstore_dist_server.h`` (N14: the server stores
the table, aggregates sparse grads, runs the optimizer server-side) +
``kvstore_dist.h :: PullRowSparse`` (N13) + the lazy sparse update
semantics of ``src/operator/optimizer_op.cc`` (row_sparse sgd/adagrad:
ONLY touched rows advance).

TPU-native shape: embedding tables too big for HBM stay in host RAM as
numpy shards (row-hashed over ``num_shards``); the training step pulls
just the rows a batch touches (``row_sparse_pull``) onto the device, and
pushes row-sparse grads back, where the SAME python optimizer the device
uses runs on cpu-context NDArrays of the touched rows — exactly the
reference's server-side-optimizer contract, without server processes.

Multi-host note: each worker process owns the full service for its own
tables in this build (BASELINE config 4 is single-host); sharding rows
across hosts would reuse this class per-host with a row->host hash and the
existing jax.distributed rendezvous — the shard layout is already
host-count-agnostic.
"""

from __future__ import annotations

import threading

import numpy as _np

from ..base import MXNetError

__all__ = ["SparsePS"]


class _Table:
    __slots__ = ("value", "lock", "state")

    def __init__(self, value):
        self.value = value          # numpy (rows, *cols) — host RAM
        self.lock = threading.Lock()
        self.state = {}             # optimizer state rows, created lazily


class SparsePS:
    """The host KV service: init/push/row_sparse_pull + server-side opt."""

    def __init__(self, num_shards=4):
        # shards bound row-id ranges for lock granularity (the reference
        # server key-ranges role); single host ⇒ logical shards
        self.num_shards = int(num_shards)
        self._tables = {}
        self._optimizer = None
        self._updaters = {}

    # -- registration -------------------------------------------------------
    def init(self, key, value):
        if key in self._tables:
            raise MXNetError(f"sparse key {key!r} already initialized")
        from ..ndarray import sparse as sp
        if isinstance(value, sp.RowSparseNDArray):
            dense = value.tostype("default").asnumpy()
        else:
            dense = value.asnumpy()
        self._tables[key] = _Table(_np.array(dense, copy=True))

    def keys(self):
        return sorted(self._tables)

    def shape(self, key):
        return self._tables[key].value.shape

    def set_optimizer(self, optimizer):
        """Server-side optimizer (reference kvstore.set_optimizer →
        server runs the updater)."""
        self._optimizer = optimizer
        self._updaters = {}

    # -- traffic ------------------------------------------------------------
    def push(self, key, grad):
        """Apply a row-sparse gradient to the table, lazily (touched rows
        only — reference row_sparse sgd_update semantics)."""
        from .. import optimizer as opt
        from .. import ndarray as nd
        from ..ndarray import sparse as sp
        tbl = self._tables.get(key)
        if tbl is None:
            raise MXNetError(f"sparse key {key!r} not initialized")
        if isinstance(grad, sp.RowSparseNDArray):
            rows = _np.asarray(grad.indices.asnumpy(), _np.int64)
            vals = _np.asarray(grad.data.asnumpy())
        else:
            rows = _np.arange(tbl.value.shape[0])
            vals = grad.asnumpy()
        if rows.size == 0:
            return
        # aggregate duplicate rows (reference merge buffer)
        uniq, inv = _np.unique(rows, return_inverse=True)
        merged = _np.zeros((len(uniq),) + vals.shape[1:], vals.dtype)
        _np.add.at(merged, inv, vals)
        with tbl.lock:
            if self._optimizer is None:
                tbl.value[uniq] += merged  # raw accumulate (no updater)
                return
            upd = self._updaters.get(key)
            if upd is None:
                upd = opt.get_updater(self._optimizer)
                self._updaters[key] = upd
            # run the SAME python optimizer on the touched row block
            # (cpu-context NDArrays — the server-side CPU update)
            w = nd.array(tbl.value[uniq])
            g = nd.array(merged)
            self._ensure_row_states(tbl, key, uniq, w)
            upd.states[key] = self._gather_states(tbl, uniq)
            upd(key, g, w)
            self._scatter_states(tbl, uniq, upd.states[key])
            tbl.value[uniq] = w.asnumpy()

    # optimizer state per ROW lives host-side too, gathered/scattered
    # around each update so adaptive optimizers (adagrad/adam) stay lazy
    def _ensure_row_states(self, tbl, key, rows, w_block):
        if "proto" not in tbl.state:
            proto = self._optimizer.create_state_multi_precision(
                key, w_block[:1])
            tbl.state["proto"] = _state_shapes(proto)
            tbl.state["rows"] = {}

    def _gather_states(self, tbl, rows):
        from .. import ndarray as nd
        proto = tbl.state["proto"]
        store = tbl.state["rows"]
        return _state_build(proto, rows, store, nd)

    def _scatter_states(self, tbl, rows, states):
        store = tbl.state["rows"]
        _state_store(states, rows, store)

    def row_sparse_pull(self, key, row_ids):
        """Gather the requested rows → RowSparseNDArray on device."""
        from .. import ndarray as nd
        from ..ndarray import sparse as sp
        tbl = self._tables.get(key)
        if tbl is None:
            raise MXNetError(f"sparse key {key!r} not initialized")
        rows = _np.unique(_np.asarray(row_ids.asnumpy(), _np.int64))
        with tbl.lock:
            block = tbl.value[rows]
        return sp.RowSparseNDArray(
            nd.array(block), nd.array(rows), tbl.value.shape)

    def pull_dense(self, key):
        from .. import ndarray as nd
        tbl = self._tables[key]
        with tbl.lock:
            return nd.array(tbl.value.copy())


# -- per-row optimizer-state plumbing ---------------------------------------

class _Leaf:
    """Template of one state leaf for ONE row (shape minus the row dim)."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape = shape
        self.dtype = dtype


def _state_shapes(proto):
    if proto is None:
        return None
    if isinstance(proto, (list, tuple)):
        return type(proto)(_state_shapes(s) for s in proto)
    return _Leaf(tuple(proto.shape[1:]), str(_np.dtype(proto.dtype)))


def _state_build(proto, rows, store, nd):
    """NDArray state blocks for these rows (zeros where never touched)."""
    if proto is None:
        return None
    if isinstance(proto, (list, tuple)):
        return type(proto)(_state_build(p, rows, store.setdefault(i, {}), nd)
                           for i, p in enumerate(proto))
    block = _np.zeros((len(rows),) + proto.shape, proto.dtype)
    for j, r in enumerate(rows):
        if r in store:
            block[j] = store[r]
    return nd.array(block)


def _state_store(states, rows, store):
    if states is None:
        return
    if isinstance(states, (list, tuple)):
        for i, s in enumerate(states):
            _state_store(s, rows, store.setdefault(i, {}))
        return
    vals = states.asnumpy()
    for j, r in enumerate(rows):
        store[r] = vals[j]
