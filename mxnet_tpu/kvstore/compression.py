"""2-bit gradient compression with error feedback (reference
src/kvstore/gradient_compression.{cc,cu,h}, N16).

Reference algorithm (GradientCompression type '2bit'): with threshold t,
each element of (grad + residual) maps to one of {+t, 0, -t}; the 2-bit
codes pack 16-to-a-float32 on the wire; the residual keeps what
quantization dropped (error feedback) so the signal is unbiased over
steps.

TPU-native shape: pack/unpack are jit-able jnp functions (4 codes per
uint8 lane — VPU-friendly bitops, no Python loops), so they fuse into the
push path.  Over the wire (dist_tpu_sync) the packed uint8 buffer is what
crosses DCN — 16x smaller than f32; each receiver dequantizes and sums.
"""

from __future__ import annotations

import functools

from ..base import MXNetError

__all__ = ["GradientCompression"]


@functools.partial(__import__("jax").jit, static_argnames=("threshold",))
def _quantize_2bit(grad, residual, threshold):
    """Returns (packed uint8 codes, new_residual).

    code 0 → 0.0, 1 → +threshold, 2 → -threshold (reference encoding).
    """
    import jax.numpy as jnp
    g = grad + residual
    t = jnp.asarray(threshold, grad.dtype)
    q = jnp.where(g >= t, t, jnp.where(g <= -t, -t, jnp.asarray(0, grad.dtype)))
    new_residual = g - q
    codes = jnp.where(g >= t, 1, jnp.where(g <= -t, 2, 0)).astype(jnp.uint8)
    flat = codes.reshape(-1)
    pad = (-flat.size) % 4
    flat = jnp.pad(flat, (0, pad))
    c = flat.reshape(-1, 4)
    packed = (c[:, 0] | (c[:, 1] << 2) | (c[:, 2] << 4)
              | (c[:, 3] << 6)).astype(jnp.uint8)
    return packed, new_residual


@functools.partial(__import__("jax").jit,
                   static_argnames=("threshold", "shape", "dtype"))
def _dequantize_2bit(packed, threshold, shape, dtype):
    import jax.numpy as jnp
    import numpy as np
    n = int(np.prod(shape)) if shape else 1
    c = packed[:, None] >> jnp.asarray([0, 2, 4, 6], jnp.uint8)[None, :]
    codes = (c & 0x3).reshape(-1)[:n]
    t = jnp.asarray(threshold, dtype)
    vals = jnp.where(codes == 1, t, jnp.where(codes == 2, -t,
                                              jnp.asarray(0, dtype)))
    return vals.reshape(shape)


@functools.partial(__import__("jax").jit,
                   static_argnames=("threshold", "shape", "dtype"))
def _dequantize_sum_2bit(packed2d, threshold, shape, dtype):
    """(P, nbytes) packed codes → sum of all P dequantized tensors, in ONE
    dispatch (the dist hot path: P separate dequantize+add dispatches per
    key per step would serialize host-side)."""
    import jax.numpy as jnp
    import numpy as np
    n = int(np.prod(shape)) if shape else 1
    c = packed2d[:, :, None] >> jnp.asarray([0, 2, 4, 6], jnp.uint8)[None, None, :]
    codes = (c & 0x3).reshape(packed2d.shape[0], -1)[:, :n]
    t = jnp.asarray(threshold, dtype)
    # sum over contributors: t * (#code1 - #code2) per element
    plus = (codes == 1).sum(axis=0).astype(dtype)
    minus = (codes == 2).sum(axis=0).astype(dtype)
    return ((plus - minus) * t).reshape(shape)


class GradientCompression:
    """Per-key compressor state (reference GradientCompression).

    ``compress(key, slot, grad)`` quantizes grad (+ the running residual
    for (key, slot)) and returns the packed codes; ``decompress`` restores
    dense values.  One residual per (key, device-slot), as the reference
    keeps one per worker.
    """

    def __init__(self, params):
        params = dict(params or {})
        ctype = params.pop("type", params.pop("compression", "2bit"))
        if ctype != "2bit":
            raise MXNetError(
                f"unsupported gradient compression type {ctype!r}: the "
                "reference implements only '2bit' "
                "(src/kvstore/gradient_compression.cc)")
        self.type = ctype
        self.threshold = float(params.pop("threshold", 0.5))
        if self.threshold <= 0:
            raise MXNetError("gradient compression threshold must be > 0")
        if params:
            raise MXNetError(f"unknown compression params {sorted(params)}")
        self._residuals = {}

    def compress(self, key, slot, grad_data):
        """grad_data: raw jax array → (packed uint8, shape, dtype)."""
        import jax.numpy as jnp
        rkey = (key, slot)
        res = self._residuals.get(rkey)
        if res is None:
            res = jnp.zeros_like(grad_data)
        packed, new_res = _quantize_2bit(grad_data, res, self.threshold)
        self._residuals[rkey] = new_res
        return packed, grad_data.shape, grad_data.dtype

    def decompress(self, packed, shape, dtype):
        import numpy as np
        return _dequantize_2bit(packed, self.threshold, tuple(shape),
                                np.dtype(dtype).name)

    def decompress_sum(self, packed2d, shape, dtype):
        """Sum of all rows' dequantized tensors, one fused dispatch."""
        import numpy as np
        return _dequantize_sum_2bit(packed2d, self.threshold, tuple(shape),
                                    np.dtype(dtype).name)
