"""Single-process KVStore ('local' / 'device' / 'nccl').

Rebuild of src/kvstore/kvstore_local.h + comm.h/comm_tree.h/kvstore_nccl.h
(N12/N15).  The reference's three reduction engines (CPU reduce, GPU P2P,
PCIe tree, NCCL ring) collapse into one path: summing jax.Arrays, which XLA
lowers to ICI collectives when the inputs live on different TPU chips.
Supports dense NDArrays and RowSparseNDArray (sparse merge = concat+segment
sum; ``row_sparse_pull(row_ids)`` retains only requested rows).
"""

from __future__ import annotations

import time as _time

from ..base import MXNetError
from .. import ndarray as nd
from .. import telemetry as _tel
from ..ndarray.ndarray import NDArray
from ..ndarray import sparse as sp
from ..telemetry import tracer as _ttrace
from . import fusion
from .base import KVStoreBase

# bytes-moved counters + call-latency histograms (ISSUE 1: comms visibility)
_M_PUSH_BYTES = _tel.counter(
    "mxnet_kvstore_push_bytes_total", "Bytes pushed into the kvstore.")
_M_PULL_BYTES = _tel.counter(
    "mxnet_kvstore_pull_bytes_total", "Bytes pulled out of the kvstore.")
_M_PUSH_SECONDS = _tel.histogram(
    "mxnet_kvstore_push_seconds", "kvstore push call latency.")
_M_PULL_SECONDS = _tel.histogram(
    "mxnet_kvstore_pull_seconds", "kvstore pull call latency.")


def _is_list(v):
    return isinstance(v, (list, tuple))


class KVStoreLocal(KVStoreBase):
    def __init__(self, name="local"):
        self._type = name
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression = None
        # gradient fusion (ISSUE 2): dense pushpull_list keys are bucketed
        # into flat buffers of at most this many bytes; <= 0 disables
        self._bucket_bytes = fusion.bucket_bytes_from_env()
        self._bucketer = None  # lazy GradBucketer (holds executable caches)

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    # -- helpers -------------------------------------------------------------
    def _reduce(self, values):
        if not _is_list(values):
            return values
        if len(values) == 1:
            return values[0]
        if isinstance(values[0], sp.RowSparseNDArray):
            return self._reduce_rowsparse(values)
        # per-device replicas are committed to their devices; stage onto the
        # first value's device and sum with a pairwise tree — O(log n) depth
        # instead of the former sequential O(n) add chain (CommDevice role),
        # and the SAME fixed-association adds the fused bucket executables
        # run, which is what keeps fused and per-key results bit-identical.
        ctx0 = values[0].ctx
        arrs = [values[0]._data] + [v.as_in_context(ctx0)._data
                                    for v in values[1:]]
        return NDArray._from_data(fusion.tree_sum(arrs), ctx=ctx0)

    @staticmethod
    def _reduce_rowsparse(values):
        import numpy as np
        import jax.numpy as jnp
        # graftcheck: ignore[GC01] — sparse merge is host-side by design:
        # np.unique over row indices has no jit-traceable analog, and
        # _fusable() keeps sparse values off the fused/dense hot path
        idx = np.concatenate([np.asarray(v.indices._data) for v in values])
        dat = jnp.concatenate([v.data._data for v in values], axis=0)
        uniq, inv = np.unique(idx, return_inverse=True)
        import jax
        merged = jax.ops.segment_sum(dat, jnp.asarray(inv),
                                     num_segments=len(uniq))
        return sp.RowSparseNDArray(
            NDArray._from_data(merged), nd.array(uniq.astype("int64")),
            values[0].shape, ctx=values[0].ctx, dtype=values[0].dtype)

    # -- API -----------------------------------------------------------------
    def init(self, key, value):
        if _is_list(key):
            for k, v in zip(key, value):
                self.init(k, v)
            return
        if key in self._store:
            raise MXNetError(f"key {key!r} already initialized")
        v = value[0] if _is_list(value) else value
        if isinstance(v, sp.BaseSparseNDArray):
            self._store[key] = v
        else:
            self._store[key] = v.copy()

    def push(self, key, value, priority=0):  # noqa: ARG002
        if _is_list(key) and _is_list(value) and len(key) > 1:
            for k, v in zip(key, value):
                self.push(k, v)
            return
        if _is_list(key):
            key = key[0]
        if key not in self._store:
            raise MXNetError(f"key {key!r} not initialized")
        with _tel.span("kvstore.push", "kvstore") as span_:
            if span_ is not _tel.NULL_SPAN:
                span_.set(key=str(key), bytes=_tel.payload_bytes(value))
            merged = self._reduce(self._compress_values(key, value))
            self._store_merged(key, merged)
        if span_ is not _tel.NULL_SPAN:
            _M_PUSH_SECONDS.observe(span_.duration_s)
            _M_PUSH_BYTES.inc(span_.attrs.get("bytes", 0))

    def _store_merged(self, key, merged):
        """Post-reduction store/update step (shared with the dist store)."""
        if self._updater is not None:
            self._updater(key, merged, self._store[key])
        else:
            stored = self._store[key]
            if isinstance(merged, sp.BaseSparseNDArray) or \
                    isinstance(stored, sp.BaseSparseNDArray):
                self._store[key] = merged
            else:
                stored._set_data(merged._data)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):  # noqa: ARG002
        if _is_list(key) and _is_list(out) and len(key) > 1 \
                and len(key) == len(out):
            for k, o in zip(key, out):
                self.pull(k, o)
            return
        if _is_list(key):
            key = key[0]
        if key not in self._store:
            raise MXNetError(f"key {key!r} not initialized")
        with _tel.span("kvstore.pull", "kvstore") as span_:
            stored = self._store[key]
            if isinstance(stored, sp.BaseSparseNDArray):
                stored = stored.tostype("default")
            outs = out if _is_list(out) else [out]
            import jax
            for o in outs:
                arr = stored._data
                if o.ctx != stored.ctx:
                    arr = jax.device_put(arr, o.ctx.jax_device())
                o._set_data(arr)
            if span_ is not _tel.NULL_SPAN:
                span_.set(key=str(key),
                          bytes=_tel.payload_bytes(stored) * len(outs))
        if span_ is not _tel.NULL_SPAN:
            _M_PULL_SECONDS.observe(span_.duration_s)
            _M_PULL_BYTES.inc(span_.attrs.get("bytes", 0))

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):  # noqa: ARG002
        if row_ids is None:
            raise MXNetError("row_sparse_pull requires row_ids")
        if _is_list(key):
            key = key[0]
        stored = self._store[key]
        if not isinstance(stored, sp.RowSparseNDArray):
            stored = sp.cast_storage(stored, "row_sparse")
        outs = out if _is_list(out) else [out]
        rids = row_ids if _is_list(row_ids) else [row_ids] * len(outs)
        for o, r in zip(outs, rids):
            ret = stored.retain(r)
            o.data._set_data(ret.data._data)
            o.indices._set_data(ret.indices._data)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    # -- fused multi-key path (ISSUE 2 tentpole; kvstore/fusion.py) ----------
    def set_bucket_size(self, mb):
        """Resize the fusion bucket bound (MB); 0 disables fusion.  Resets
        the bucketer so cached plans rebuild against the new bound."""
        self._bucket_bytes = int(float(mb) * (1 << 20))
        self._bucketer = None

    def _fusable(self, key, vlist):
        """A key may enter a bucket only if its reduce+store+pull composes
        to exactly the per-key path: dense stored value, dense pushed
        values, no gradient compression (subclasses add their own vetoes)."""
        if self._compression is not None:
            return False
        stored = self._store.get(key)
        if stored is None or isinstance(stored, sp.BaseSparseNDArray):
            return False
        return not any(isinstance(v, sp.BaseSparseNDArray) for v in vlist)

    def _allreduce_flat(self, flat):
        """Cross-worker reduction of one flat bucket; identity in-process
        (the dist store overrides this with one psum per bucket)."""
        return flat

    def _fused_needs_flat(self):
        """True when buckets must flatten into one buffer for a cross-worker
        wire step (dist store, multi-process).  In-process the flat buffer
        is pure memcpy overhead, so buckets reduce per-key in one dispatch
        instead."""
        return False

    def _split_fusable(self, keys, values):
        """Classify keys into fused-eligible vs per-key fallback positions
        (shared by pushpull_list and pushpull_flat so the fallback
        contract cannot diverge between the two entry points)."""
        fused, fallback, vlists = [], [], []
        for j, key in enumerate(keys):
            v = values[j]
            vlist = list(v) if _is_list(v) else [v]
            vlists.append(vlist)
            (fused if self._fusable(key, vlist) else fallback).append(j)
        return fused, fallback, vlists

    @staticmethod
    def _stage_bucket(bucket, vlists):
        """One bucket's replica-major raw arrays, staged onto the primary
        replica's device; returns (arrays, prim_ctx)."""
        import jax
        prim_ctx = vlists[bucket.positions[0]][0].ctx
        prim_dev = None  # resolved lazily; staging is rare
        arrays = []
        for r in range(bucket.n_rep):
            for p in bucket.positions:
                v = vlists[p][r]
                a = v._data
                if v.ctx != prim_ctx:
                    if prim_dev is None:
                        prim_dev = prim_ctx.jax_device()
                    a = jax.device_put(a, prim_dev)
                arrays.append(a)
        return arrays, prim_ctx

    def pushpull_list(self, keys, values, outs, priority=0):
        if self._updater is not None or self._bucket_bytes <= 0:
            # update-on-kvstore runs the optimizer inside push — the fused
            # path has no update hook, so take the per-key loop verbatim
            return KVStoreBase.pushpull_list(self, keys, values, outs,
                                             priority=priority)
        fused, fallback, vlists = self._split_fusable(keys, values)
        for j in fallback:
            self.pushpull(keys[j], values[j], out=outs[j], priority=priority)
        if _ttrace._ENABLED:
            fusion.record_fallback(len(fallback))
        if fused:
            self._fused_pushpull([keys[j] for j in fused],
                                 [vlists[j] for j in fused],
                                 [outs[j] for j in fused])

    def _fused_pushpull(self, keys, vlists, outs):
        import jax
        bucketer = self._bucketer
        if bucketer is None:
            bucketer = self._bucketer = fusion.GradBucketer(self._bucket_bytes)
        signature = tuple((tuple(v[0].shape), str(v[0].dtype), len(v))
                          for v in vlists)
        buckets = bucketer.plan(signature)
        needs_flat = self._fused_needs_flat()
        enabled = _ttrace._ENABLED
        with _tel.span("kvstore.fused_pushpull", "kvstore") as span_:
            total_bytes = 0
            for b in buckets:
                t0 = _time.perf_counter_ns() if enabled else 0
                try:
                    arrays, prim_ctx = self._stage_bucket(b, vlists)
                    if needs_flat:
                        # wire strategy: one flat buffer → ONE collective
                        flat = bucketer.reduce_flat(b, arrays)
                        flat = self._allreduce_flat(flat)
                        parts = bucketer.unflatten(b, flat)
                    elif b.n_rep == 1:
                        parts = arrays  # identity reduction: no device work
                    else:
                        parts = bucketer.reduce_bucket(b, arrays)
                except Exception as exc:
                    from ..resilience import ResilienceError
                    if isinstance(exc, ResilienceError) or needs_flat:
                        # cluster-level failures (timeouts, exhausted
                        # retries, injected deaths) — and ANY rank-local
                        # failure in multi-process mode — must propagate:
                        # replaying per-key here while peers ran the fused
                        # collective would desynchronize the global
                        # collective order
                        raise
                    # graceful degradation (ISSUE 3), in-process only: a
                    # failing fused bucket executable must not take the
                    # step down — the pushed values are untouched, so
                    # replaying its keys per-key recomputes the same result
                    self._fused_bucket_fallback(b, keys, vlists, outs)
                    continue
                for p, arr in zip(b.positions, parts):
                    self._store[keys[p]]._set_data(arr)
                    o = outs[p]
                    for out_nd in (o if _is_list(o) else [o]):
                        if out_nd is None:
                            continue
                        oarr = arr
                        if out_nd.ctx != prim_ctx:
                            oarr = jax.device_put(arr,
                                                  out_nd.ctx.jax_device())
                        out_nd._set_data(oarr)
                if enabled:
                    fusion.record_bucket(b, _time.perf_counter_ns() - t0)
                    total_bytes += b.nbytes * b.n_rep
            if enabled:
                fusion.record_pushpull()
                span_.set(keys=len(keys), buckets=len(buckets),
                          bytes=total_bytes)

    def pushpull_flat(self, keys, values, outs, priority=0):
        """Fused allreduce returning FLAT per-bucket reduced-gradient
        buffers for direct consumption by the fused optimizer
        (optimizer_fusion.fused_update_flat): bucketed keys reduce flat —
        one collective per bucket on the dist wire — and are NOT
        unflattened; neither the store copies nor ``outs`` are written
        for them (their grad buffers keep local pre-reduction values;
        that skipped round trip is the point).  Non-fusable keys take the
        per-key pushpull into ``outs`` exactly like pushpull_list.

        Returns ``[(key_list, shapes, sizes, flat_array), ...]``, or
        None — fall back to pushpull_list — when fusion is off, the
        store owns the update, or no cross-process wire step exists
        (``_fused_needs_flat``): in-process the flat buffer is pure copy
        overhead (per-key reduction + per-param fused update is strictly
        cheaper), so the handoff only engages where the flat buffer has
        to exist anyway for the wire collective.  Failures propagate —
        this path is multi-process by construction, and a per-key replay
        while peers ran the collective would desync the global order
        (same contract as _fused_pushpull's needs_flat branch)."""
        if self._updater is not None or self._bucket_bytes <= 0 \
                or not self._fused_needs_flat():
            return None
        fused, fallback, vlists = self._split_fusable(keys, values)
        for j in fallback:
            self.pushpull(keys[j], values[j], out=outs[j], priority=priority)
        enabled = _ttrace._ENABLED
        if enabled:
            fusion.record_fallback(len(fallback))
        if not fused:
            return []
        bucketer = self._bucketer
        if bucketer is None:
            bucketer = self._bucketer = fusion.GradBucketer(self._bucket_bytes)
        fkeys = [keys[j] for j in fused]
        fvlists = [vlists[j] for j in fused]
        signature = tuple((tuple(v[0].shape), str(v[0].dtype), len(v))
                          for v in fvlists)
        buckets = bucketer.plan(signature)
        result = []
        with _tel.span("kvstore.fused_pushpull_flat", "kvstore") as span_:
            for b in buckets:
                t0 = _time.perf_counter_ns() if enabled else 0
                arrays, _prim = self._stage_bucket(b, fvlists)
                flat = bucketer.reduce_flat(b, arrays)
                flat = self._allreduce_flat(flat)
                result.append(([fkeys[p] for p in b.positions],
                               b.shapes, b.sizes, flat))
                if enabled:
                    fusion.record_bucket(b, _time.perf_counter_ns() - t0)
            if enabled:
                fusion.record_pushpull()
                span_.set(keys=len(fused), buckets=len(buckets))
        return result

    def _fused_bucket_fallback(self, bucket, keys, vlists, outs):
        """Replay one failed fused bucket through the per-key path
        (graceful degradation; counted in
        mxnet_resilience_fallbacks_total + the fused fallback counter)."""
        import warnings
        from .. import resilience as _res
        # shared counter counts degradation EVENTS (one per bucket);
        # per-key accounting rides the fused fallback-keys counter
        _res.record_fallback()
        fusion.record_bucket_error(len(bucket.positions))
        warnings.warn(
            f"fused pushpull bucket of {len(bucket.positions)} keys failed; "
            "falling back to per-key pushpull", stacklevel=3)
        for p in bucket.positions:
            v = vlists[p]
            self.pushpull(keys[p], v if len(v) > 1 else v[0], out=outs[p])

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def set_optimizer(self, optimizer):
        """update_on_kvstore path — reference runs this on the PS server; the
        local store runs it inline at push time."""
        from .. import optimizer as opt
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        """2-bit + error-feedback compression on pushed gradients
        (reference set_gradient_compression / gradient_compression.cc).
        Each replica's contribution is quantized to {-t, 0, +t} (residual
        carried per (key, replica)) before the reduction — the same
        worker-side quantization the reference applies before transmitting
        to the PS."""
        from .compression import GradientCompression
        self._compression = GradientCompression(compression_params)

    def _compress_values(self, key, values):
        """Quantize→dequantize each replica's dense contribution."""
        if self._compression is None:
            return values
        vlist = values if _is_list(values) else [values]
        if any(isinstance(v, sp.BaseSparseNDArray) for v in vlist):
            return values  # reference compresses dense grads only
        out = []
        for slot, v in enumerate(vlist):
            packed, shape, dtype = self._compression.compress(
                key, slot, v._data)
            out.append(NDArray._from_data(
                self._compression.decompress(packed, shape, dtype),
                ctx=v.ctx))
        return out if _is_list(values) else out[0]

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on this kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on this kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def _barrier(self):
        nd.waitall()
