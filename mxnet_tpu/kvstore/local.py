"""Single-process KVStore ('local' / 'device' / 'nccl').

Rebuild of src/kvstore/kvstore_local.h + comm.h/comm_tree.h/kvstore_nccl.h
(N12/N15).  The reference's three reduction engines (CPU reduce, GPU P2P,
PCIe tree, NCCL ring) collapse into one path: summing jax.Arrays, which XLA
lowers to ICI collectives when the inputs live on different TPU chips.
Supports dense NDArrays and RowSparseNDArray (sparse merge = concat+segment
sum; ``row_sparse_pull(row_ids)`` retains only requested rows).
"""

from __future__ import annotations

from ..base import MXNetError
from .. import ndarray as nd
from .. import telemetry as _tel
from ..ndarray.ndarray import NDArray
from ..ndarray import sparse as sp
from .base import KVStoreBase

# bytes-moved counters + call-latency histograms (ISSUE 1: comms visibility)
_M_PUSH_BYTES = _tel.counter(
    "mxnet_kvstore_push_bytes_total", "Bytes pushed into the kvstore.")
_M_PULL_BYTES = _tel.counter(
    "mxnet_kvstore_pull_bytes_total", "Bytes pulled out of the kvstore.")
_M_PUSH_SECONDS = _tel.histogram(
    "mxnet_kvstore_push_seconds", "kvstore push call latency.")
_M_PULL_SECONDS = _tel.histogram(
    "mxnet_kvstore_pull_seconds", "kvstore pull call latency.")


def _is_list(v):
    return isinstance(v, (list, tuple))


class KVStoreLocal(KVStoreBase):
    def __init__(self, name="local"):
        self._type = name
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression = None

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    # -- helpers -------------------------------------------------------------
    def _reduce(self, values):
        if not _is_list(values):
            return values
        if len(values) == 1:
            return values[0]
        if isinstance(values[0], sp.RowSparseNDArray):
            return self._reduce_rowsparse(values)
        # per-device replicas are committed to their devices; stage onto the
        # first value's device then sum — one XLA add chain (CommDevice role)
        out = values[0]
        for v in values[1:]:
            out = out + v.as_in_context(out.ctx)
        return out

    @staticmethod
    def _reduce_rowsparse(values):
        import numpy as np
        import jax.numpy as jnp
        idx = np.concatenate([np.asarray(v.indices._data) for v in values])
        dat = jnp.concatenate([v.data._data for v in values], axis=0)
        uniq, inv = np.unique(idx, return_inverse=True)
        import jax
        merged = jax.ops.segment_sum(dat, jnp.asarray(inv),
                                     num_segments=len(uniq))
        return sp.RowSparseNDArray(
            NDArray._from_data(merged), nd.array(uniq.astype("int64")),
            values[0].shape, ctx=values[0].ctx, dtype=values[0].dtype)

    # -- API -----------------------------------------------------------------
    def init(self, key, value):
        if _is_list(key):
            for k, v in zip(key, value):
                self.init(k, v)
            return
        if key in self._store:
            raise MXNetError(f"key {key!r} already initialized")
        v = value[0] if _is_list(value) else value
        if isinstance(v, sp.BaseSparseNDArray):
            self._store[key] = v
        else:
            self._store[key] = v.copy()

    def push(self, key, value, priority=0):  # noqa: ARG002
        if _is_list(key) and _is_list(value) and len(key) > 1:
            for k, v in zip(key, value):
                self.push(k, v)
            return
        if _is_list(key):
            key = key[0]
        if key not in self._store:
            raise MXNetError(f"key {key!r} not initialized")
        with _tel.span("kvstore.push", "kvstore") as span_:
            if span_ is not _tel.NULL_SPAN:
                span_.set(key=str(key), bytes=_tel.payload_bytes(value))
            merged = self._reduce(self._compress_values(key, value))
            self._store_merged(key, merged)
        if span_ is not _tel.NULL_SPAN:
            _M_PUSH_SECONDS.observe(span_.duration_s)
            _M_PUSH_BYTES.inc(span_.attrs.get("bytes", 0))

    def _store_merged(self, key, merged):
        """Post-reduction store/update step (shared with the dist store)."""
        if self._updater is not None:
            self._updater(key, merged, self._store[key])
        else:
            stored = self._store[key]
            if isinstance(merged, sp.BaseSparseNDArray) or \
                    isinstance(stored, sp.BaseSparseNDArray):
                self._store[key] = merged
            else:
                stored._set_data(merged._data)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):  # noqa: ARG002
        if _is_list(key) and _is_list(out) and len(key) > 1 \
                and len(key) == len(out):
            for k, o in zip(key, out):
                self.pull(k, o)
            return
        if _is_list(key):
            key = key[0]
        if key not in self._store:
            raise MXNetError(f"key {key!r} not initialized")
        with _tel.span("kvstore.pull", "kvstore") as span_:
            stored = self._store[key]
            if isinstance(stored, sp.BaseSparseNDArray):
                stored = stored.tostype("default")
            outs = out if _is_list(out) else [out]
            import jax
            for o in outs:
                arr = stored._data
                if o.ctx != stored.ctx:
                    arr = jax.device_put(arr, o.ctx.jax_device())
                o._set_data(arr)
            if span_ is not _tel.NULL_SPAN:
                span_.set(key=str(key),
                          bytes=_tel.payload_bytes(stored) * len(outs))
        if span_ is not _tel.NULL_SPAN:
            _M_PULL_SECONDS.observe(span_.duration_s)
            _M_PULL_BYTES.inc(span_.attrs.get("bytes", 0))

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):  # noqa: ARG002
        if row_ids is None:
            raise MXNetError("row_sparse_pull requires row_ids")
        if _is_list(key):
            key = key[0]
        stored = self._store[key]
        if not isinstance(stored, sp.RowSparseNDArray):
            stored = sp.cast_storage(stored, "row_sparse")
        outs = out if _is_list(out) else [out]
        rids = row_ids if _is_list(row_ids) else [row_ids] * len(outs)
        for o, r in zip(outs, rids):
            ret = stored.retain(r)
            o.data._set_data(ret.data._data)
            o.indices._set_data(ret.indices._data)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def set_optimizer(self, optimizer):
        """update_on_kvstore path — reference runs this on the PS server; the
        local store runs it inline at push time."""
        from .. import optimizer as opt
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        """2-bit + error-feedback compression on pushed gradients
        (reference set_gradient_compression / gradient_compression.cc).
        Each replica's contribution is quantized to {-t, 0, +t} (residual
        carried per (key, replica)) before the reduction — the same
        worker-side quantization the reference applies before transmitting
        to the PS."""
        from .compression import GradientCompression
        self._compression = GradientCompression(compression_params)

    def _compress_values(self, key, values):
        """Quantize→dequantize each replica's dense contribution."""
        if self._compression is None:
            return values
        vlist = values if _is_list(values) else [values]
        if any(isinstance(v, sp.BaseSparseNDArray) for v in vlist):
            return values  # reference compresses dense grads only
        out = []
        for slot, v in enumerate(vlist):
            packed, shape, dtype = self._compression.compress(
                key, slot, v._data)
            out.append(NDArray._from_data(
                self._compression.decompress(packed, shape, dtype),
                ctx=v.ctx))
        return out if _is_list(values) else out[0]

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on this kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on this kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def _barrier(self):
        nd.waitall()
