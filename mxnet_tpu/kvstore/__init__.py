"""KVStore — the distributed key-value synchronization API.

Rebuild of src/kvstore/* + python/mxnet/kvstore (N12-N17, P13, SURVEY §5.8).
Semantics preserved: ``mx.kv.create(type)`` factory with named-key
init/push/pull/pushpull/broadcast/row_sparse_pull, rank/num_workers,
``set_optimizer`` (update-on-kvstore), ``set_gradient_compression``,
``_barrier``.

TPU-native mapping (SURVEY §7.1):
 - 'local' / 'device' / 'nccl' → single-process reduction.  Pushing a LIST of
   per-device values sums them with one XLA add chain (the CommDevice role);
   there are no P2P copy trees to manage — ICI routing belongs to XLA.
 - 'dist_sync' / 'dist_device_sync' / 'dist_tpu_sync' → multi-process
   ``jax.distributed`` + psum over the global device mesh (see dist.py).  No
   scheduler/server processes: the DCN bootstrap plays the scheduler role and
   the optimizer stays on device.
 - 'dist_async' → documented drop: fully-async SGD has no sane TPU-native
   analog (SURVEY §7.1 table); creation raises with that explanation.
"""

from __future__ import annotations

from ..base import MXNetError
from .base import KVStoreBase  # noqa: F401
from . import fusion  # noqa: F401  (GradBucketer — ISSUE 2 gradient fusion)
from .local import KVStoreLocal
from .dist import KVStoreDistTPUSync


def num_data_devices():
    """Devices the data-parallel axis would span in this process."""
    import jax
    return jax.local_device_count()


def create(name="local", **kwargs):
    """mx.kv.create — reference src/kvstore/kvstore.cc :: KVStore::Create."""
    if not isinstance(name, str):
        raise MXNetError("name must be a string")
    n = name.lower()
    if n in ("local", "local_update_cpu", "local_allreduce_cpu",
             "local_allreduce_device", "device", "nccl"):
        return KVStoreLocal(name=n)
    if n in ("dist_sync", "dist_device_sync", "dist_tpu_sync", "dist"):
        return KVStoreDistTPUSync(name=n, **kwargs)
    if n in ("dist_async", "dist_sync_device_async"):
        raise MXNetError(
            "kvstore 'dist_async' is intentionally unsupported in the TPU "
            "rebuild: asynchronous parameter-server SGD has no TPU-native "
            "equivalent (no server processes exist; gradients reduce via "
            "synchronous XLA collectives). Use 'dist_tpu_sync'.")
    # pluggable backends (reference 1.7 KVStoreBase.register — how the
    # horovod backend plugged in upstream): registered classes resolve by
    # their class name, after the built-ins so they can't shadow those
    klass = KVStoreBase.registered(n)
    if klass is not None:
        return klass(**kwargs)
    if n == "horovod":
        raise MXNetError("horovod backend not available in this build; use "
                         "'dist_tpu_sync' (or KVStoreBase.register a "
                         "custom backend class named Horovod)")
    raise MXNetError(f"unknown kvstore type {name!r}")


KVStore = KVStoreLocal  # handle-style alias
