"""'dist_tpu_sync' — the distributed KVStore over XLA collectives.

Rebuild of the whole reference PS stack (kvstore_dist.h worker N13,
kvstore_dist_server.h N14, ps-lite N17, SURVEY §3.4/§5.8) as its TPU-native
replacement: NO scheduler/server/worker processes and no ZeroMQ —
``jax.distributed.initialize`` (DCN rendezvous = the scheduler role) forms one
global device mesh, and every push+pull of a dense key lowers to a psum over
the data axis riding ICI (+DCN between hosts).  The optimizer never moves to
a server: it runs on device after the reduce (update_on_kvstore=False
semantics; set_optimizer keeps API parity by running updates locally
post-reduction).

Eager API contract: push(key, grad); pull(key, out) — the psum executes
eagerly via a jitted collective over the process-spanning mesh.  For the
fused fast path (reduction inside the jitted train step) use
mxnet_tpu.parallel.build_train_step, which this kvstore's semantics guarantee
to be equivalent.

Big keys honor MXNET_KVSTORE_BIGARRAY_BOUND by switching psum →
reduce_scatter+all_gather (bandwidth-optimal on large dense arrays).
"""

from __future__ import annotations

import os

from ..base import MXNetError
from .. import config
from .. import ndarray as nd
from .local import KVStoreLocal


class KVStoreDistTPUSync(KVStoreLocal):
    def __init__(self, name="dist_tpu_sync"):
        super().__init__(name=name)
        self._initialized = False
        self._mesh = None
        self._psum_cache = {}

    # -- bootstrap (the dmlc_tracker/scheduler role) -------------------------
    def _ensure_dist(self):
        if self._initialized:
            return
        import jax
        # Under a pod launcher these env vars are set (tools/launch.py analog
        # writes them); single-process fallback keeps tests runnable anywhere.
        coord = os.environ.get("MXNET_DIST_COORDINATOR") \
            or os.environ.get("JAX_COORDINATOR_ADDRESS")
        if coord and jax.process_count() == 1:
            try:
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=int(os.environ.get("MXNET_DIST_NUM_WORKERS",
                                                     "1")),
                    process_id=int(os.environ.get("MXNET_DIST_RANK", "0")))
            except RuntimeError:
                pass  # already initialized by the launcher
        self._initialized = True

    @property
    def rank(self):
        self._ensure_dist()
        import jax
        return jax.process_index()

    @property
    def num_workers(self):
        self._ensure_dist()
        import jax
        return jax.process_count()

    # -- collective reduce ---------------------------------------------------
    def _allreduce(self, arr):
        """Sum this key's value across all processes (ICI+DCN psum).

        Each process contributes its locally reduced value; the sum is
        computed by a jitted collective over a process-spanning mesh.  The
        value is laid out sharded over the "data" axis (each process's
        contribution on its own devices) and reduced with psum, so the
        traffic rides ICI between chips and DCN between hosts — XLA picks
        ring/tree routing.  reduce_scatter+all_gather for keys above
        MXNET_KVSTORE_BIGARRAY_BOUND is what this psum already lowers to on
        large inputs (XLA does the decomposition); the bound is kept as an
        env knob for parity but no longer changes the code path.
        """
        import jax
        if jax.process_count() <= 1:
            return arr
        from jax.experimental import multihost_utils
        # stack one slice per process on the global mesh, then sum: the
        # canonical eager cross-process allreduce in multi-controller JAX
        gathered = multihost_utils.process_allgather(arr, tiled=False)
        return gathered.sum(axis=0)

    def push(self, key, value, priority=0):
        self._ensure_dist()
        if isinstance(key, (list, tuple)) and len(key) > 1:
            for k, v in zip(key, value):
                self.push(k, v)
            return
        if isinstance(key, (list, tuple)):
            key, value = key[0], value[0] if isinstance(value, (list, tuple)) \
                else value
        merged = self._reduce(value if isinstance(value, (list, tuple))
                              else [value])
        from ..ndarray import sparse as sp
        if isinstance(merged, sp.BaseSparseNDArray):
            super().push(key, merged)
            return
        reduced = nd.NDArray._from_data(self._allreduce(merged._data),
                                        ctx=merged.ctx)
        super().push(key, reduced)

    def _barrier(self):
        self._ensure_dist()
        import jax
        if jax.process_count() > 1:
            # all-processes sync point: a tiny global psum
            import jax.numpy as jnp
            jax.block_until_ready(self._allreduce(jnp.zeros((1,))))
        nd.waitall()

    def barrier(self):
        self._barrier()
