"""'dist_tpu_sync' — the distributed KVStore over XLA collectives.

Rebuild of the whole reference PS stack (kvstore_dist.h worker N13,
kvstore_dist_server.h N14, ps-lite N17, SURVEY §3.4/§5.8) as its TPU-native
replacement: NO scheduler/server/worker processes and no ZeroMQ —
``jax.distributed.initialize`` (DCN rendezvous = the scheduler role) forms one
global device mesh, and every push+pull of a dense key lowers to a psum over
the data axis riding ICI (+DCN between hosts).  The optimizer never moves to
a server: it runs on device after the reduce (update_on_kvstore=False
semantics; set_optimizer keeps API parity by running updates locally
post-reduction).

Eager API contract: push(key, grad); pull(key, out) — the psum executes
eagerly via a jitted collective over the process-spanning mesh.  For the
fused fast path (reduction inside the jitted train step) use
mxnet_tpu.parallel.build_train_step, which this kvstore's semantics guarantee
to be equivalent.

Big keys honor MXNET_KVSTORE_BIGARRAY_BOUND by switching psum →
reduce_scatter+all_gather (bandwidth-optimal on large dense arrays).
"""

from __future__ import annotations

import os

from ..base import MXNetError
from .. import config
from .. import ndarray as nd
from .. import telemetry as _tel
from ..resilience import Deadline, KVStoreTimeoutError, Retry
from ..resilience import chaos as _chaos
from ..resilience import heartbeat as _hb
from .local import KVStoreLocal

# registry get-or-create: same handles local.py registered
_M_PUSH_BYTES = _tel.counter("mxnet_kvstore_push_bytes_total")
_M_PUSH_SECONDS = _tel.histogram("mxnet_kvstore_push_seconds")
_M_ALLREDUCE_BYTES = _tel.counter(
    "mxnet_kvstore_allreduce_bytes_total",
    "Bytes entering the cross-process allreduce collective.")
_M_ALLREDUCE_SECONDS = _tel.histogram(
    "mxnet_kvstore_allreduce_seconds",
    "Cross-process allreduce latency (dispatch + transfer).")


def _merge_rowsparse(vals):
    """Concat replica row-sparse grads into one (the PS merges duplicate
    rows); a single value passes through."""
    if len(vals) == 1:
        return vals[0]
    from ..ndarray import sparse as sp
    from .. import ndarray as nd
    import numpy as _np
    rows = _np.concatenate([_np.asarray(v.indices.asnumpy(), _np.int64)
                            for v in vals])
    data = _np.concatenate([v.data.asnumpy() for v in vals], axis=0)
    return sp.RowSparseNDArray(nd.array(data), nd.array(rows),
                               vals[0].shape)


class KVStoreDistTPUSync(KVStoreLocal):
    def __init__(self, name="dist_tpu_sync"):
        super().__init__(name=name)
        self._initialized = False
        self._mesh = None
        self._psum_cache = {}
        self._sparse_ps = None  # host KV service, created on first sparse key
        # resilience policies (ISSUE 3): every blocking cross-process call
        # is deadline-bounded (a dead peer raises KVStoreTimeoutError
        # instead of hanging) and transient failures retry with backoff
        self._retry = Retry(site="kvstore.allreduce")
        self._deadline = Deadline(site="kvstore.allreduce")

    def _ps(self):
        if self._sparse_ps is None:
            from .sparse_ps import SparsePS
            self._sparse_ps = SparsePS()
        return self._sparse_ps

    # -- sparse keys: the host PS path (reference kvstore_dist_server role) --
    def init(self, key, value):
        from ..ndarray import sparse as sp
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.init(k, v)
            return
        v = value[0] if isinstance(value, (list, tuple)) else value
        if isinstance(v, sp.BaseSparseNDArray) or \
                getattr(v, "stype", "default") == "row_sparse":
            self._ps().init(key, v)
            return
        super().init(key, value)

    def set_optimizer(self, optimizer):
        super().set_optimizer(optimizer)
        self._ps().set_optimizer(optimizer)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if isinstance(key, (list, tuple)) and len(key) == 1:
            key = key[0]
        if self._is_sparse_key(key):
            from ..ndarray import sparse as sp
            dense = self._ps().pull_dense(key)
            outs = out if isinstance(out, (list, tuple)) else [out]
            for o in outs:
                if isinstance(o, sp.BaseSparseNDArray):
                    if ignore_sparse:
                        continue  # reference: sparse outs skipped here
                    raise MXNetError(
                        "pull of a sparse-PS key into a sparse out is not "
                        "supported; use row_sparse_pull(key, row_ids=...)")
                o._set_data(dense.as_in_context(o.ctx)._data)
            return
        return super().pull(key, out=out, priority=priority,
                            ignore_sparse=ignore_sparse)

    def _is_sparse_key(self, key):
        return self._sparse_ps is not None \
            and not isinstance(key, (list, tuple)) \
            and key in self._sparse_ps._tables

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        if isinstance(key, (list, tuple)) and len(key) == 1:
            key = key[0]
        if self._is_sparse_key(key):
            if row_ids is None:
                raise MXNetError("row_sparse_pull requires row_ids")
            if out is None:
                rids = row_ids[0] if isinstance(row_ids, (list, tuple)) \
                    else row_ids
                return self._ps().row_sparse_pull(key, rids)
            outs = out if isinstance(out, (list, tuple)) else [out]
            rids = row_ids if isinstance(row_ids, (list, tuple)) \
                else [row_ids] * len(outs)
            ret = None
            for o, r in zip(outs, rids):  # per-out row sets (base contract)
                ret = self._ps().row_sparse_pull(key, r)
                o.data._set_data(ret.data._data)
                o.indices._set_data(ret.indices._data)
            return ret
        return super().row_sparse_pull(key, out=out, priority=priority,
                                       row_ids=row_ids)

    # -- bootstrap (the dmlc_tracker/scheduler role) -------------------------
    def _ensure_dist(self):
        if self._initialized:
            return
        import jax
        # elastic liveness (ISSUE 11): under the elastic controller the
        # heartbeat dir is injected per incarnation — start beating
        # BEFORE the rendezvous so even bring-up time is observable, and
        # walk the phase to 'running' once the world forms.  The rank in
        # each beat is re-read from the (re-numbered) MXNET_DIST_RANK of
        # THIS incarnation, so a restarted survivor reports its new rank.
        hb_on = _hb.enabled()
        if hb_on:
            _hb.start()
            _hb.set_phase("bringup")
        # Under a pod launcher these env vars are set (tools/launch.py analog
        # writes them); single-process fallback keeps tests runnable anywhere.
        coord = config.get("MXNET_DIST_COORDINATOR") \
            or os.environ.get("JAX_COORDINATOR_ADDRESS")
        if coord and jax.process_count() == 1:
            nproc = config.get_int("MXNET_DIST_NUM_WORKERS", 1)
            rank = config.get_int("MXNET_DIST_RANK", 0)
            kwargs = dict(coordinator_address=coord, num_processes=nproc,
                          process_id=rank)
            t = self._deadline.timeout_s
            if t and t > 0:
                # bound the rendezvous itself: a missing peer must error,
                # not hang the bring-up forever
                kwargs["initialization_timeout"] = max(1, int(t))
            try:
                try:
                    jax.distributed.initialize(**kwargs)
                except TypeError:  # older jax without initialization_timeout
                    kwargs.pop("initialization_timeout", None)
                    jax.distributed.initialize(**kwargs)
            except RuntimeError as e:
                msg = str(e).lower()
                if "already" in msg or "only be called once" in msg \
                        or "must be called before" in msg:
                    # benign re-initialize (jax phrases this as "should
                    # only be called once" / "must be called before any
                    # JAX computations", not "already") — but verify the
                    # world actually formed below: for a multi-worker job
                    # this same error can mean bring-up FAILED because the
                    # backend was touched first, and proceeding would
                    # silently train unsynchronized
                    pass
                elif "timed out" in msg or "timeout" in msg \
                        or "deadline" in msg:
                    _tel.flightrec.dump("deadline.dist.bringup", exc=e)
                    # surface the bring-up failure to the elastic
                    # controller: a 'failed' heartbeat BEFORE 'running'
                    # classifies this as a rendezvous problem, which
                    # restarts at the SAME world size (no rank died)
                    _hb.mark_failed(
                        f"bringup-timeout: rank {rank}/{nproc} at {coord} "
                        f"after {t:g}s")
                    raise KVStoreTimeoutError(
                        f"distributed bring-up: rank {rank} could not "
                        f"rendezvous with all {nproc} workers at {coord} "
                        f"within {t:g}s (MXNET_KVSTORE_TIMEOUT_S) — a peer "
                        "never arrived") from e
                else:
                    raise
            if nproc > 1 and jax.process_count() == 1:
                _hb.mark_failed("bringup-failed: backend initialized "
                                "before the dist kvstore")
                raise MXNetError(
                    f"distributed bring-up: MXNET_DIST_NUM_WORKERS={nproc} "
                    "but the process group never formed (the jax backend "
                    "was initialized before the dist kvstore). Create the "
                    "kvstore — or call jax.distributed.initialize — before "
                    "any array/computation touches the backend.")
        self._initialized = True
        if hb_on:
            _hb.set_phase("running")
        # rank-tag this process's telemetry (ISSUE 10): snapshots exported
        # into MXNET_TELEMETRY_DIR and flight-recorder dumps carry the
        # rank, and rank 0 merges them into one job-wide view
        try:
            _tel.aggregate.set_rank(jax.process_index())
        except Exception:  # noqa: BLE001 — telemetry must not break bring-up
            pass

    @property
    def rank(self):
        self._ensure_dist()
        import jax
        return jax.process_index()

    @property
    def num_workers(self):
        self._ensure_dist()
        import jax
        return jax.process_count()

    # -- collective reduce ---------------------------------------------------
    def _proc_mesh(self):
        """1-D mesh with ONE device per process (this process's first local
        device carries its contribution).  Cached; the psum over its axis is
        the compiled cross-process collective (ICI within a host's chips,
        DCN between hosts — XLA routes it)."""
        if self._mesh is None:
            import jax
            import numpy as _np
            by_proc = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, d)
            devs = [by_proc[p] for p in sorted(by_proc)]
            self._mesh = jax.sharding.Mesh(_np.array(devs), ("proc",))
        return self._mesh

    def _psum_fn(self, shape, dtype):
        """Jitted psum over the process axis for this (shape, dtype)."""
        key = (tuple(shape), str(dtype))
        fn = self._psum_cache.get(key)
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as P
            from ..kernels import shard_map_compat
            shard_map = shard_map_compat()
            mesh = self._proc_mesh()

            def reduce_(x):  # x block: (1, *shape) per device
                return jax.lax.psum(x[0], "proc")

            fn = jax.jit(shard_map(reduce_, mesh=mesh, in_specs=P("proc"),
                                   out_specs=P()))
            self._psum_cache[key] = fn
        return fn

    def _allreduce(self, arr):
        """Sum this key's value across all processes.

        A REAL compiled collective (no host staging): each process's locally
        reduced value becomes one shard of a (P, *shape) global array laid
        over the process mesh; a jitted ``shard_map``-psum over the ``proc``
        axis produces the replicated sum, O(size) memory per process.  XLA
        lowers the psum to reduce-scatter + all-gather on large inputs, so
        MXNET_KVSTORE_BIGARRAY_BOUND remains an env knob for parity but no
        longer selects a different code path.

        Resilience: the collective is deadline-bounded (a dead peer raises
        KVStoreTimeoutError instead of wedging) and transient failures in
        the PRE-dispatch region retry with backoff.  Once multi-process,
        neither timeouts nor post-dispatch transients are retried —
        re-entering a collective that peers already ran (or never joined)
        would desynchronize the global collective order.  In-process the
        whole attempt retries (no peers to desync).
        """
        import jax
        if jax.process_count() <= 1:
            return self._retry.call(self._allreduce_attempt, arr)
        self._retry.call(self._chaos_gate)
        return self._allreduce_collective(arr)

    @staticmethod
    def _chaos_gate():
        if _chaos._ACTIVE:
            _chaos.hit("kvstore.allreduce")

    def _allreduce_attempt(self, arr):
        self._chaos_gate()
        import jax
        if jax.process_count() <= 1:
            return arr
        return self._allreduce_collective(arr)

    def _allreduce_collective(self, arr):
        import jax
        import jax.numpy as jnp
        with _tel.span("kvstore.allreduce", "kvstore") as span_:
            if span_ is not _tel.NULL_SPAN:
                span_.set(bytes=int(arr.nbytes))

            def collective():
                garr = self._make_global(arr)
                out = self._psum_fn(arr.shape, arr.dtype)(garr)
                # fully replicated output: this process reads its local copy
                return jnp.asarray(out.addressable_data(0))

            res = self._deadline.call(collective)
        if span_ is not _tel.NULL_SPAN:
            _M_ALLREDUCE_SECONDS.observe(span_.duration_s)
            _M_ALLREDUCE_BYTES.inc(int(arr.nbytes))
        return res

    def _make_global(self, arr):
        """Local (\\*shape) value → global (P, \\*shape) array whose p-th
        shard is process p's contribution, laid on the process mesh."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self._proc_mesh()
        my_dev = next(d for d in mesh.devices.flat
                      if d.process_index == jax.process_index())
        local = jax.device_put(jnp.asarray(arr)[None], my_dev)
        gshape = (jax.process_count(),) + tuple(arr.shape)
        return jax.make_array_from_single_device_arrays(
            gshape, NamedSharding(mesh, P("proc")), [local])

    def _allgather_fn(self, shape, dtype):
        """Jitted all-gather over the process axis (compression wire path)."""
        key = ("ag", tuple(shape), str(dtype))
        fn = self._psum_cache.get(key)
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as P
            from ..kernels import shard_map_compat
            shard_map = shard_map_compat()
            mesh = self._proc_mesh()

            def gather(x):  # block (1, *shape) → (P, *shape) replicated
                return jax.lax.all_gather(x[0], "proc")

            fn = jax.jit(shard_map(gather, mesh=mesh, in_specs=P("proc"),
                                   out_specs=P()))
            self._psum_cache[key] = fn
        return fn

    def push(self, key, value, priority=0):
        self._ensure_dist()
        if isinstance(key, (list, tuple)) and len(key) > 1:
            for k, v in zip(key, value):
                self.push(k, v)
            return
        if isinstance(key, (list, tuple)):
            key, value = key[0], value[0] if isinstance(value, (list, tuple)) \
                else value
        if self._is_sparse_key(key):
            vals = value if isinstance(value, (list, tuple)) else [value]
            # aggregate replica grads into ONE grad, then ONE server update
            # (reference merge-buffer-then-update; per-replica updates would
            # advance stateful optimizers once per replica)
            self._ps().push(key, _merge_rowsparse(vals))
            return
        # NOTE: local replica reduction only — per-process compression and
        # the cross-process wire step happen below, once, so super().push
        # must not re-compress (we call _store_merged directly)
        with _tel.span("kvstore.push", "kvstore") as span_:
            if span_ is not _tel.NULL_SPAN:
                span_.set(key=str(key), bytes=_tel.payload_bytes(value))
            merged = self._reduce(value if isinstance(value, (list, tuple))
                                  else [value])
            from ..ndarray import sparse as sp
            if isinstance(merged, sp.BaseSparseNDArray):
                self._store_merged(key, merged)
            else:
                import jax
                if self._compression is not None and jax.process_count() > 1:
                    # 2-bit wire path: all-gather the PACKED codes (16x less
                    # DCN traffic than f32 — reference kvstore_dist.h
                    # quantized push), then each process dequantizes every
                    # contribution and sums
                    packed, shape, dtype = self._compression.compress(
                        key, "dist", merged._data)
                    gathered = self._gather_packed(packed)
                    total = self._compression.decompress_sum(
                        gathered, shape, dtype)
                    reduced = nd.NDArray._from_data(total, ctx=merged.ctx)
                else:
                    if self._compression is not None:
                        merged = self._compress_values(key, merged)
                    reduced = nd.NDArray._from_data(
                        self._allreduce(merged._data), ctx=merged.ctx)
                self._store_merged(key, reduced)
        if span_ is not _tel.NULL_SPAN:
            _M_PUSH_SECONDS.observe(span_.duration_s)
            _M_PUSH_BYTES.inc(span_.attrs.get("bytes", 0))

    # -- fused multi-key path (ISSUE 2): one psum per BUCKET ----------------
    def _fusable(self, key, vlist):
        # sparse-PS keys take the host KV service; everything else follows
        # the local rules (dense, uncompressed)
        return super()._fusable(key, vlist) and not self._is_sparse_key(key)

    def _allreduce_flat(self, flat):
        # the whole bucket crosses processes as ONE collective — at BERT
        # scale that is ~17 psums per step instead of ~200
        return self._allreduce(flat)

    def _fused_needs_flat(self):
        import jax
        return jax.process_count() > 1

    def pushpull_list(self, keys, values, outs, priority=0):
        self._ensure_dist()
        return super().pushpull_list(keys, values, outs, priority=priority)

    def pushpull_flat(self, keys, values, outs, priority=0):
        # flat handoff to the fused optimizer: the bucket crosses
        # processes as ONE psum (_allreduce_flat) and is consumed flat
        self._ensure_dist()
        return super().pushpull_flat(keys, values, outs, priority=priority)

    def _gather_packed(self, packed):
        """(nbytes,) uint8 local codes → (P, nbytes) from every process."""
        import jax.numpy as jnp
        garr = self._make_global(packed)
        out = self._allgather_fn(packed.shape, packed.dtype)(garr)
        return jnp.asarray(out.addressable_data(0))

    def _barrier(self):
        self._ensure_dist()
        if _chaos._ACTIVE:
            _chaos.hit("dist.barrier")
        import jax
        if jax.process_count() > 1:
            # all-processes sync point: a tiny global psum, deadline-bounded
            # through _allreduce so a dead peer raises instead of hanging
            import jax.numpy as jnp
            try:
                jax.block_until_ready(self._allreduce(jnp.zeros((1,))))
            except KVStoreTimeoutError as e:
                rank, n = jax.process_index(), jax.process_count()
                missing = sorted(set(range(n)) - {rank})
                raise KVStoreTimeoutError(
                    f"dist.barrier: rank {rank} reached the barrier but at "
                    f"least one of ranks {missing} (world size {n}) never "
                    f"arrived within {self._deadline.timeout_s:g}s "
                    "(MXNET_KVSTORE_TIMEOUT_S)") from e
        nd.waitall()
