"""KVStoreBase — the pluggable backend interface (reference 1.7
python/mxnet/kvstore/base.py :: KVStoreBase.register)."""

from __future__ import annotations

from ..base import MXNetError

_BACKENDS = {}


class KVStoreBase:
    @staticmethod
    def register(klass):
        """Class decorator: make ``klass`` creatable via
        ``mx.kv.create(klass.__name__)`` (reference 1.7
        python/mxnet/kvstore/base.py::KVStoreBase.register — the extension
        point the horovod backend used upstream).  Case-insensitive; a
        re-register under the same name replaces the previous class (the
        reference warns-and-overwrites; notebooks re-run cells)."""
        name = klass.__name__.lower()
        prev = _BACKENDS.get(name)
        if prev is not None and prev is not klass:
            import warnings
            warnings.warn(f"KVStore backend {name!r} already registered "
                          f"({prev.__name__}); overwriting with "
                          f"{klass.__name__}", stacklevel=2)
        _BACKENDS[name] = klass
        return klass

    @staticmethod
    def registered(name):
        """Look up a registered backend class by type string (or None)."""
        return _BACKENDS.get(name.lower())

    @staticmethod
    def list_backends():
        return sorted(_BACKENDS)

    # capability strings (reference KVStoreBase.OPTIMIZER/...)
    OPTIMIZER = "optimizer"

    def is_capable(self, capability):
        return capability == self.OPTIMIZER

    @property
    def type(self):
        raise NotImplementedError

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def broadcast(self, key, value, out):
        raise NotImplementedError

    def barrier(self):
        """Synchronize all workers.  Default: delegate to the internal
        ``_barrier`` when the backend has one (local stores wait for
        outstanding async work; the dist store runs a deadline-bounded
        collective sync that raises KVStoreTimeoutError — never hangs —
        when a peer is missing), else no-op for single-worker backends."""
        inner = getattr(self, "_barrier", None)
        if inner is not None:
            inner()

    def pushpull(self, key, value, out=None, priority=0):
        raise NotImplementedError

    def pushpull_list(self, keys, values, outs, priority=0):
        """Multi-key pushpull in one call — the gradient-fusion entry point
        (Trainer._allreduce_grads routes its whole dense grad list here).
        Base implementation: the plain per-key loop; KVStoreLocal overrides
        it with bucketed flat-buffer fusion (kvstore/fusion.py)."""
        for k, v, o in zip(keys, values, outs):
            self.pushpull(k, v, out=o, priority=priority)
