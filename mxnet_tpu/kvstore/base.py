"""KVStoreBase — the pluggable backend interface (reference 1.7
python/mxnet/kvstore/base.py :: KVStoreBase.register)."""

from __future__ import annotations

from ..base import MXNetError

_BACKENDS = {}


class KVStoreBase:
    @staticmethod
    def register(klass):
        _BACKENDS[klass.__name__.lower()] = klass
        return klass

    # capability strings (reference KVStoreBase.OPTIMIZER/...)
    OPTIMIZER = "optimizer"

    def is_capable(self, capability):
        return capability == self.OPTIMIZER

    @property
    def type(self):
        raise NotImplementedError

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def broadcast(self, key, value, out):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        raise NotImplementedError
