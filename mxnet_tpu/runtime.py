"""mx.runtime — feature introspection (reference src/libinfo.cc N22 +
python/mxnet/runtime.py).  Features reflect what this build/host actually
supports; compile-time CUDA/MKLDNN flags map to their TPU-stack analogs."""

from __future__ import annotations

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _detect():
    import jax
    feats = {}
    platforms = {d.platform for d in jax.devices()}
    feats["TPU"] = "tpu" in platforms or "axon" in platforms
    feats["CPU"] = True
    feats["CUDA"] = False          # TPU-native build
    feats["CUDNN"] = False
    feats["MKLDNN"] = False        # XLA:CPU plays this role
    feats["XLA"] = True
    feats["PALLAS"] = _has_pallas()
    feats["BF16"] = True
    feats["F16C"] = True
    feats["BLAS_OPEN"] = True
    feats["LAPACK"] = True
    feats["OPENCV"] = _has("cv2")
    feats["DIST_KVSTORE"] = True   # dist_tpu_sync (jax.distributed)
    feats["INT64_TENSOR_SIZE"] = True
    feats["SIGNAL_HANDLER"] = False
    feats["PROFILER"] = True
    feats["OPENMP"] = False
    feats["SSE"] = False
    feats["TENSORRT"] = False
    feats["TVM_OP"] = False
    return feats


def _has(mod):
    import importlib.util
    return importlib.util.find_spec(mod) is not None


def _has_pallas():
    try:
        from jax.experimental import pallas  # noqa: F401
        return True
    except ImportError:
        return False


class Features(dict):
    """mx.runtime.Features() — dict of Feature (reference LibInfo::Features)."""

    instance = None

    def __new__(cls):
        if cls.instance is None:
            inst = super().__new__(cls)
            inst.update({k: Feature(k, v) for k, v in _detect().items()})
            cls.instance = inst
        return cls.instance

    def __init__(self):
        super().__init__()

    def is_enabled(self, name):
        name = name.upper()
        if name not in self:
            raise RuntimeError(f"feature {name!r} does not exist")
        return self[name].enabled

    def __repr__(self):
        return str(list(self.values()))


def feature_list():
    return list(Features().values())
