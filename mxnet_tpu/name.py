"""mx.name — symbol auto-naming (reference python/mxnet/name.py).

``NameManager`` hands each anonymous symbol a unique, readable name
(``dot0``, ``fullyconnected1``, …) from per-hint counters; ``Prefix``
prepends a fixed prefix (the building block under Gluon's name scopes).
Thread-local stack, context-manager protocol — same surface as upstream.
"""

from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = [NameManager()]
    return _tls.stack


class NameManager:
    """Per-hint counters: get(None, 'dot') → 'dot0', 'dot1', …"""

    def __init__(self):
        self._counter = {}

    @staticmethod
    def current():
        return _stack()[-1]

    def get(self, name, hint):
        if name is not None:
            return name
        n = self._counter.get(hint, 0)
        self._counter[hint] = n + 1
        return f"{hint}{n}"

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()


class Prefix(NameManager):
    """Auto-names carry a fixed prefix (reference name.py :: Prefix)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        if name is not None:
            return name
        return self._prefix + super().get(None, hint)
