"""Stateful RNG facade over JAX's functional PRNG.

Rebuild of the reference's ``python/mxnet/random.py`` + per-device
counter-based generators (src/common/random_generator.h, N21).  MXNet exposes
a *stateful* RNG (``mx.random.seed(42); mx.nd.random.uniform(...)``); JAX is
functional (explicit keys).  We hide a per-context key behind the stateful
API: every draw splits the context's key, so call order determines the stream
exactly like the reference's per-device generators.  Parity is
distribution-level, not bitwise (SURVEY §7.3 item 7).
"""

from __future__ import annotations

import threading

import numpy as _np

__all__ = ["seed", "get_key", "fork_key", "generator_of"]

_state = threading.local()
_DEFAULT_SEED = 0


class _CtxGenerator:
    """Mirrors a per-device random generator: one evolving key per context."""

    __slots__ = ("key",)

    def __init__(self, seed_val):
        import jax
        self.key = jax.random.PRNGKey(seed_val)

    def next_key(self):
        import jax
        self.key, sub = jax.random.split(self.key)
        return sub


def _generators():
    if not hasattr(_state, "gens"):
        _state.gens = {}
        _state.seed = _DEFAULT_SEED
    return _state.gens


def _dev_offset(dev_key):
    """Deterministic per-device stream offset (stable across processes —
    Python's str hash is randomized, so zlib.crc32 instead)."""
    import zlib
    return zlib.crc32(f"{dev_key[0]}:{dev_key[1]}".encode()) & 0xFFFF


def generator_of(ctx):
    """The stateful generator for a context (created on first use)."""
    gens = _generators()
    k = (ctx.device_type, ctx.device_id)
    if k not in gens:
        # Offset per device so different devices get different streams from
        # the same seed — parity with the reference's per-device generators.
        gens[k] = _CtxGenerator(_state.seed + _dev_offset(k))
    return gens[k]


def seed(seed_state, ctx="all"):
    """mx.random.seed — reseed generators (all contexts or one).

    Reference: python/mxnet/random.py :: seed(seed_state, ctx='all').
    """
    if not isinstance(seed_state, (int, _np.integer)):
        raise ValueError("seed_state must be an integer")
    seed_state = int(seed_state)
    gens = _generators()
    if ctx == "all":
        _state.seed = seed_state
        gens.clear()
    else:
        k = (ctx.device_type, ctx.device_id)
        gens[k] = _CtxGenerator(seed_state + _dev_offset(k))


def get_key(ctx=None):
    """Split and return a fresh PRNG key from the context's stream.

    Inside a CachedOp trace (hybridize), keys come from the traced key pushed
    by the tracer instead of the stateful stream — otherwise a dropout mask
    would be baked into the compiled graph as a constant.
    """
    if getattr(_state, "trace_keys", None):
        import jax
        cur = _state.trace_keys[-1]
        _state.trace_keys[-1], sub = jax.random.split(cur)
        _state.trace_uses[-1] += 1
        return sub
    if ctx is None:
        from .context import current_context
        ctx = current_context()
    return generator_of(ctx).next_key()


class trace_key_scope:
    """Context manager: route get_key() to splits of a traced key."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        if not hasattr(_state, "trace_keys"):
            _state.trace_keys = []
            _state.trace_uses = []
        _state.trace_keys.append(self._key)
        _state.trace_uses.append(0)
        self.uses = 0
        return self

    def __exit__(self, *exc):
        _state.trace_keys.pop()
        self.uses = _state.trace_uses.pop()
        return False


def in_trace():
    """True while a trace_key_scope is active (CachedOp / TrainStep tracing).
    Used by components that must behave ctx-agnostically under tracers
    (e.g. Parameter replica selection)."""
    return bool(getattr(_state, "trace_keys", None))


def fork_key(ctx=None, num=2):
    import jax
    k = get_key(ctx)
    return jax.random.split(k, num)
