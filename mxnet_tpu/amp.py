"""Automatic mixed precision — TPU-native, bf16-first.

Reference: ``python/mxnet/contrib/amp/amp.py`` (P17) + the
``src/nnvm/low_precision_pass.cc`` graph pass (N10) + the per-op dtype
lists in ``contrib/amp/lists/symbol_fp16.py``.

TPU-native design (SURVEY §7.1 AMP row): instead of monkey-patching every
generated op namespace (the reference's trick) or rewriting nnvm graphs,
casts are inserted at the single imperative-dispatch chokepoint
(``ops.registry.invoke``) that BOTH the eager path and the ``hybridize()``
trace flow through.  ``amp.init()`` installs a cast hook that, per op:

 - casts float32/float64 inputs of matmul/conv-heavy ops (``TARGET_OPS``)
   down to the target dtype — these hit the MXU, where bf16 is the fast
   path;
 - casts low-precision inputs of numerically sensitive ops (``FP32_OPS``:
   softmax, norms, exp/log, losses) up to float32;
 - casts all float inputs of dtype-agnostic multi-input ops
   (``WIDEST_OPS``) to the widest float dtype present (the reference's
   ``amp_multicast`` semantics).

Because the hook runs inside the jit trace, XLA sees the casts as part of
the program and fuses them into neighbors — there is no eager cast cost.

The default target is **bfloat16**: same exponent range as float32, so no
loss scaling is needed and ``LossScaler`` stays at scale 1.  ``float16`` is
accepted for API parity and enables the reference's dynamic loss-scaling
algorithm (scale halves on overflow, doubles after ``scale_window`` clean
steps — ``contrib/amp/loss_scaler.py``).
"""

from __future__ import annotations

import contextlib

import numpy as _np

from .base import MXNetError

__all__ = ["init", "init_trainer", "scale_loss", "unscale", "LossScaler",
           "convert_model", "convert_hybrid_block",
           "list_lp16_ops", "list_fp32_ops", "list_widest_ops"]

# ---------------------------------------------------------------------------
# op lists (reference contrib/amp/lists/symbol_fp16.py, curated to this
# registry's op surface)
# ---------------------------------------------------------------------------

# matmul/conv-dominated ops: run in the target low precision (MXU fast path)
TARGET_OPS = {
    "dot", "batch_dot", "matmul", "einsum",
    "FullyConnected", "Convolution", "Deconvolution", "RNN",
    "contrib.interleaved_matmul_selfatt_qk",
    "contrib.interleaved_matmul_selfatt_valatt",
    "contrib.interleaved_matmul_encdec_qk",
    "contrib.interleaved_matmul_encdec_valatt",
    "contrib.masked_selfatt",
}

# numerically sensitive ops: always accumulate in float32
FP32_OPS = {
    "softmax", "log_softmax", "softmin", "SoftmaxActivation", "SoftmaxOutput",
    "softmax_cross_entropy", "gumbel_softmax",
    "BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm", "L2Normalization",
    "LRN", "norm", "linalg.norm", "mean", "sum", "sum_axis", "nansum",
    "logsumexp", "cumsum",
    "exp", "expm1", "log", "log1p", "log2", "log10",
    "erf", "erfinv", "rsqrt", "sqrt", "square",
    "linalg.slogdet", "linalg.sumlogdiag",
}

# dtype-agnostic multi-input ops: promote every float input to the widest
# float dtype present (amp_multicast semantics)
WIDEST_OPS = {
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "add_n", "concat", "stack", "where",
}

_FLOAT_KINDS = ("f",)  # numpy kind for float dtypes (bf16 reports 'V' via
                       # ml_dtypes? no — ml_dtypes registers kind 'f')


def list_lp16_ops():
    """Ops cast to the low-precision target (reference list_fp16_ops)."""
    return sorted(TARGET_OPS)


def list_fp32_ops():
    return sorted(FP32_OPS)


def list_widest_ops():
    return sorted(WIDEST_OPS)


# ---------------------------------------------------------------------------
# state + dispatch hook
# ---------------------------------------------------------------------------

class _AmpState:
    __slots__ = ("active", "target_dtype", "target_ops", "fp32_ops",
                 "widest_ops")

    def __init__(self):
        self.active = False
        self.target_dtype = None
        self.target_ops = frozenset()
        self.fp32_ops = frozenset()
        self.widest_ops = frozenset()


_state = _AmpState()


def _is_float(dt):
    try:
        d = _np.dtype(dt)
    except TypeError:
        return False
    if d.kind == "f":
        return True
    # ml_dtypes extended floats (bfloat16 et al.) report numpy kind 'V'
    import ml_dtypes
    return d == _np.dtype(ml_dtypes.bfloat16)


def _cast_hook(op_name, arrays):
    """Installed as ops.registry dispatch hook; must be jax-traceable."""
    import jax.numpy as jnp
    st = _state
    if op_name in st.target_ops:
        tgt = st.target_dtype
        return [a.astype(tgt)
                if hasattr(a, "dtype") and _is_float(a.dtype)
                and _np.dtype(a.dtype).itemsize > 2 else a
                for a in arrays]
    if op_name in st.fp32_ops:
        return [a.astype(jnp.float32)
                if hasattr(a, "dtype") and _is_float(a.dtype)
                and _np.dtype(a.dtype).itemsize < 4 else a
                for a in arrays]
    if op_name in st.widest_ops:
        fdts = [_np.dtype(a.dtype) for a in arrays
                if hasattr(a, "dtype") and _is_float(a.dtype)]
        if len(fdts) > 1 and len(set(fdts)) > 1:
            widest = max(fdts, key=lambda d: d.itemsize)
            return [a.astype(widest)
                    if hasattr(a, "dtype") and _is_float(a.dtype) else a
                    for a in arrays]
    return arrays


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP process-wide (reference amp.init()).

    target_dtype : 'bfloat16' (TPU default) or 'float16' (API parity; the
        reference only knows float16).
    target_precision_ops : extra op names to run in the target dtype.
    conditional_fp32_ops / fp32_ops : extra op names forced to float32
        (the reference's conditional triples collapse to names here — the
        conditions were cuDNN-specific).
    """
    import ml_dtypes
    from .ops import registry as _reg

    if hasattr(target_dtype, "name"):
        target_dtype = _np.dtype(target_dtype).name
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError(
            f"amp target_dtype must be bfloat16 or float16, got {target_dtype!r}")
    tgt = ml_dtypes.bfloat16 if target_dtype == "bfloat16" else _np.float16

    st = _state
    st.target_dtype = tgt
    st.target_ops = frozenset(TARGET_OPS) | frozenset(target_precision_ops or ())
    extra_fp32 = set(fp32_ops or ())
    for item in (conditional_fp32_ops or ()):
        # reference passes (op_name, attr, values) triples
        extra_fp32.add(item[0] if isinstance(item, (tuple, list)) else item)
    st.fp32_ops = (frozenset(FP32_OPS) | extra_fp32) - st.target_ops
    st.widest_ops = frozenset(WIDEST_OPS) - st.target_ops - st.fp32_ops
    st.active = True

    _reg.set_dispatch_cast_hook(_cast_hook)

    # matmul accumulation stays f32 on MXU; inputs are what we cast
    import jax
    jax.config.update("jax_default_matmul_precision", "default")


def off():
    """Disable AMP (test helper; reference has no un-init)."""
    from .ops import registry as _reg
    _state.active = False
    _reg.set_dispatch_cast_hook(None)


# ---------------------------------------------------------------------------
# loss scaling
# ---------------------------------------------------------------------------

class LossScaler:
    """Dynamic loss scaler (reference contrib/amp/loss_scaler.py).

    bf16 needs no scaling (f32 exponent range): ``loss_scale`` stays 1 and
    ``has_overflow`` still guards against inf/nan grads (skip-step safety).
    fp16 uses the reference dynamic algorithm: start high, halve on
    overflow, double after ``scale_window`` clean steps.
    """

    def __init__(self, init_scale=None, scale_factor=2.0, scale_window=2000,
                 target_dtype="float16"):
        self._dynamic = str(target_dtype) == "float16"
        if init_scale is None:
            init_scale = 2.0 ** 16 if self._dynamic else 1.0
        self.loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._unskipped = 0

    def has_overflow(self, grad_arrays):
        """True if any gradient is non-finite; updates the dynamic scale.

        One device sync total: the per-array non-finite counts accumulate
        symbolically and a single bool() fetches the result."""
        import jax.numpy as jnp
        bad = None
        for g in grad_arrays:
            data = g._data if hasattr(g, "_data") else g
            if not _is_float(data.dtype):
                continue
            n = jnp.logical_not(jnp.isfinite(data)).sum()
            bad = n if bad is None else bad + n
        finite = bad is None or not bool(bad > 0)
        if not finite:
            if self._dynamic:
                self.loss_scale = max(self.loss_scale / self._scale_factor, 1.0)
            self._unskipped = 0
            return True
        self._unskipped += 1
        if self._dynamic and self._unskipped >= self._scale_window:
            self.loss_scale *= self._scale_factor
            self._unskipped = 0
        return False


def init_trainer(trainer):
    """Attach a LossScaler to a gluon Trainer (reference amp.init_trainer)."""
    if not _state.active:
        raise MXNetError("call amp.init() before amp.init_trainer()")
    tname = str(_np.dtype(_state.target_dtype))
    trainer._amp_loss_scaler = LossScaler(target_dtype=tname)
    trainer._amp_original_scale = trainer._scale


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """``with amp.scale_loss(loss, trainer) as scaled: scaled.backward()``.

    Scales the loss up by the current loss scale and folds the inverse into
    the trainer's gradient rescale so ``trainer.step`` sees unscaled
    gradients (reference scale_loss flow).
    """
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    trainer._amp_grads_unscaled = False  # new step: grads will carry the scale
    if scaler is None or scaler.loss_scale == 1.0:
        yield loss
        return
    s = scaler.loss_scale
    trainer._scale = trainer._amp_original_scale / s
    if isinstance(loss, (list, tuple)):
        yield [l * s for l in loss]
    else:
        yield loss * s


def unscale(trainer):
    """Divide current gradients by the loss scale in place (reference
    amp.unscale — for clipping between backward() and step())."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req == "null":
            continue
        for g in p.list_grad():
            g *= inv
    trainer._scale = trainer._amp_original_scale
    trainer._amp_grads_unscaled = True  # step() must not divide again


# ---------------------------------------------------------------------------
# model conversion
# ---------------------------------------------------------------------------

_KEEP_FP32_PARAM_MARKERS = ("gamma", "beta", "running_mean", "running_var",
                            "moving_mean", "moving_var")


def convert_hybrid_block(block, target_dtype="bfloat16",
                         cast_optional_params=False):
    """Cast a HybridBlock's parameters for low-precision inference
    (reference amp.convert_hybrid_block over the nnvm ReducePrecision pass).

    Matmul/conv weights go to ``target_dtype``; norm-layer statistics and
    affine params stay float32 (the reference's fp32 list) unless
    ``cast_optional_params``.  Dispatch-level casts from ``amp.init`` handle
    activations; this handles the stored params.  Returns ``block``.
    """
    import ml_dtypes
    tgt = ml_dtypes.bfloat16 if str(target_dtype) == "bfloat16" else _np.float16
    for name, p in block.collect_params().items():
        if p._data is None:
            continue
        if not cast_optional_params and any(
                m in name for m in _KEEP_FP32_PARAM_MARKERS):
            continue
        if _is_float(p.dtype):
            p.cast(tgt)
    # rebuild any hybridize caches so the new dtypes retrace
    for b in _iter_blocks(block):
        if getattr(b, "_cached_op", None) is not None:
            b._cached_op = None
    return block


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16",
                  target_dtype_ops=None, fp32_ops=None,
                  conditional_fp32_ops=None, excluded_sym_names=()):
    """Symbolic-API conversion (reference amp.convert_model).

    The graph itself needs no rewrite — executor dispatch applies the same
    cast hook — so this casts the parameter dicts and returns
    ``(sym, arg_params, aux_params)`` like the reference.
    """
    del target_dtype_ops, fp32_ops, conditional_fp32_ops  # hook-level already
    import ml_dtypes
    tgt = ml_dtypes.bfloat16 if str(target_dtype) == "bfloat16" else _np.float16
    excluded = set(excluded_sym_names)

    def conv(d):
        out = {}
        for k, v in d.items():
            if k not in excluded and _is_float(v.dtype) and not any(
                    m in k for m in _KEEP_FP32_PARAM_MARKERS):
                out[k] = v.astype(tgt)
            else:
                out[k] = v
        return out

    return sym, conv(arg_params), conv(aux_params)


def _iter_blocks(block):
    yield block
    for child in getattr(block, "_children", {}).values():
        yield from _iter_blocks(child)
