"""Weight initializers (reference python/mxnet/initializer.py, P21).

API parity: registry + string lookup (``init='xavier'``), ``InitDesc`` name-
pattern dispatch (arrays named *_bias get zeros, *gamma ones, ...), the
standard zoo: Uniform/Normal/Constant/Zero/One/Orthogonal/Xavier/MSRAPrelu/
Bilinear/LSTMBias.  Draws go through the stateful RNG facade so
``mx.random.seed`` controls them.
"""

from __future__ import annotations

import numpy as _np

from .base import MXNetError

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def get(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    key = name.lower()
    if key not in _REGISTRY:
        raise MXNetError(f"unknown initializer {name!r}; "
                         f"known: {sorted(_REGISTRY)}")
    return _REGISTRY[key](**kwargs)


class InitDesc(str):
    """Array-name descriptor carrying init attrs (reference InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        s = super().__new__(cls, name)
        s.attrs = attrs or {}
        s.global_init = global_init
        return s


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        init_attr = desc.attrs.get("__init__", "")
        if init_attr:
            get(init_attr)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_one(desc, arr)
        elif name.endswith("beta"):
            self._init_zero(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        else:
            self._init_weight(desc, arr)

    init_weight = __call__

    def _init_bias(self, desc, arr):
        self._init_zero(desc, arr)

    def _init_zero(self, desc, arr):  # noqa: ARG002
        arr[:] = 0.0

    def _init_one(self, desc, arr):  # noqa: ARG002
        arr[:] = 1.0

    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _rand(self, kind, arr, **kw):
        import jax
        from . import random as _rnd
        key = _rnd.get_key(arr.ctx)
        if kind == "uniform":
            val = jax.random.uniform(key, arr.shape, arr.dtype,
                                     minval=kw["low"], maxval=kw["high"])
        else:
            val = jax.random.normal(key, arr.shape, arr.dtype) * kw["sigma"]
        arr._set_data(val)

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register
class Zero(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, desc, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, arr):
        self._rand("uniform", arr, low=-self.scale, high=self.scale)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, arr):
        self._rand("normal", arr, sigma=self.sigma)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, desc, arr):
        import jax
        from . import random as _rnd
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        key = _rnd.get_key(arr.ctx)
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(key, (nout, nin), minval=-1.0, maxval=1.0)
        else:
            tmp = jax.random.normal(key, (nout, nin))
        u, _, v = _np.linalg.svd(_np.asarray(tmp), full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        arr[:] = _np.asarray(self.scale * q.reshape(arr.shape), dtype=arr.dtype)


def _fan(shape, factor_type):
    hw = int(_np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = (shape[1] if len(shape) > 1 else shape[0]) * hw
    fan_out = shape[0] * hw
    if factor_type == "avg":
        return (fan_in + fan_out) / 2.0
    if factor_type == "in":
        return fan_in
    return fan_out


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        factor = _fan(arr.shape, self.factor_type)
        scale = _np.sqrt(self.magnitude / max(factor, 1.0))
        if self.rnd_type == "uniform":
            self._rand("uniform", arr, low=-scale, high=scale)
        else:
            self._rand("normal", arr, sigma=scale)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, desc, arr):
        weight = _np.zeros(int(_np.prod(arr.shape)), dtype=_np.float32)
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(weight.size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = _np.asarray(weight.reshape(shape), dtype=arr.dtype)


@register
class LSTMBias(Initializer):
    """Forget-gate bias 1.0, everything else 0 (gate order i,f,g,o)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        v = _np.zeros(arr.shape, dtype=_np.float32)
        n = arr.shape[0] // 4
        v[n:2 * n] = self.forget_bias
        arr[:] = _np.asarray(v, dtype=arr.dtype)


@register
class Mixed(Initializer):
    def __init__(self, patterns, initializers):
        super().__init__()
        import re
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, desc, arr):
        for pat, init in self.map:
            if pat.match(desc):
                init(desc, arr)
                return
        raise MXNetError(f"no initializer pattern matched {desc!r}; "
                         "add a '.*' catch-all")


# string aliases the reference accepts
_REGISTRY["zeros"] = Zero
_REGISTRY["ones"] = One
_REGISTRY["msra_prelu"] = MSRAPrelu
_REGISTRY["gaussian"] = Normal
