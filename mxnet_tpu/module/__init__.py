"""mx.mod — legacy Module API (reference python/mxnet/module/, P11)."""

from .module import Module, BaseModule  # noqa: F401
from .bucketing_module import BucketingModule  # noqa: F401
