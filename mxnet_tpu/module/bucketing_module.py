"""BucketingModule (reference python/mxnet/module/bucketing_module.py):
variable-length sequence training — one Module per bucket key, params shared.

TPU note: each bucket is a separate XLA specialization (static shapes); the
reference's shared-memory-pool trick becomes XLA's per-shape executable cache.
"""

from __future__ import annotations

import logging

from ..base import MXNetError
from .module import BaseModule, Module


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, **kwargs):
        super().__init__(logger)
        if default_bucket_key is None:
            raise MXNetError("default_bucket_key required")
        self._sym_gen = sym_gen
        self._default_key = default_bucket_key
        self._context = context
        self._kwargs = kwargs
        self._buckets = {}
        self._curr_module = None
        self._curr_key = None
        self._arg_cache = None
        self._opt_args = None

    @property
    def symbol(self):
        return self._curr_module.symbol

    def _module_for(self, key):
        if key not in self._buckets:
            sym, data_names, label_names = self._sym_gen(key)
            mod = Module(sym, data_names, label_names, self.logger,
                         self._context, **self._kwargs)
            self._buckets[key] = mod
        return self._buckets[key]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):  # noqa: ARG002
        self._curr_module = self._module_for(self._default_key)
        self._curr_key = self._default_key
        self._curr_module.bind(data_shapes, label_shapes, for_training,
                               force_rebind=force_rebind)
        self.binded = True
        self.for_training = for_training

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        mod = self._module_for(bucket_key)
        if not mod.binded:
            mod.bind(data_shapes, label_shapes, self.for_training)
            if self.params_initialized and self._curr_module is not None:
                arg, aux = self._curr_module.get_params()
                mod.set_params(arg, aux)
            if self.optimizer_initialized and self._opt_args is not None:
                mod.init_optimizer(**self._opt_args)
        else:
            # sync shared params into the target bucket
            if self._curr_module is not None and self.params_initialized:
                arg, aux = self._curr_module.get_params()
                mod.set_params(arg, aux)
        self._curr_module = mod
        self._curr_key = bucket_key

    def init_params(self, **kwargs):
        self._curr_module.init_params(**kwargs)
        self.params_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    def set_params(self, arg_params, aux_params, **kwargs):
        self._curr_module.set_params(arg_params, aux_params, **kwargs)
        self.params_initialized = True

    def init_optimizer(self, **kwargs):
        self._opt_args = dict(kwargs)
        self._curr_module.init_optimizer(**kwargs)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = getattr(data_batch, "bucket_key", self._default_key)
        if key != self._curr_key:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()
        # propagate updated params back into other bound buckets lazily at
        # the next switch (set_params in switch_bucket)

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)
