"""Module — the legacy symbolic training loop.

Rebuild of python/mxnet/module/{base_module,module,executor_group}.py (P11):
bind → one Executor (the DataParallelExecutorGroup's batch-splitting role is
subsumed by the parallel trainer's sharded step on TPU — a single executor
spans the mesh), init_params/init_optimizer, forward/backward/update,
fit()/score()/predict(), save_checkpoint/load.
"""

from __future__ import annotations

import logging

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from .. import ndarray as nd
from .. import metric as _metric
from .. import optimizer as _opt
from .. import initializer as _init
from ..model import BatchEndParam, save_checkpoint, load_params


def nd_concat_batch(parts):
    """Concat per-ctx output slices along the batch axis (scalars stack)."""
    if parts[0].ndim == 0:
        return nd.stack(*parts, axis=0)
    return nd.concat(*parts, dim=0)

__all__ = ["BaseModule", "Module"]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False

    # -- high-level loops ----------------------------------------------------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):  # noqa: ARG002
        if num_epoch is None:
            raise MXNetError("num_epoch must be specified for fit")
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer or _init.Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params,
                            force_init=force_init)
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        validation_metric = validation_metric or eval_metric
        if monitor is not None:
            monitor.install()  # dispatch-level hook (reference installs per
            # executor; our dispatch ledger is global — see mx.monitor)

        for epoch in range(begin_epoch, num_epoch):
            eval_metric.reset()
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                if monitor is not None:
                    monitor.toc_print()
                self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                          eval_metric=eval_metric,
                                          locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(param)
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            if epoch_end_callback is not None:
                arg, aux = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg, aux)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)

    def score(self, eval_data, eval_metric, num_batch=None, reset=True,
              epoch=0, **kwargs):  # noqa: ARG002
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, reset=True):
        if reset:
            eval_data.reset()
        outputs = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            outputs.append([o.copy() for o in self.get_outputs()])
        if not outputs:
            return []
        import jax.numpy as jnp
        from ..ndarray.ndarray import NDArray
        num_out = len(outputs[0])
        return [NDArray._from_data(
            jnp.concatenate([b[i]._data for b in outputs], axis=0))
            for i in range(num_out)]

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    # subclass surface
    def bind(self, *a, **k):
        raise NotImplementedError

    def forward(self, *a, **k):
        raise NotImplementedError

    def backward(self, *a, **k):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):  # noqa: ARG002
        super().__init__(logger)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        ctxs = context if context is not None else current_context()
        self._contexts = list(ctxs) if isinstance(ctxs, (list, tuple)) \
            else [ctxs]
        self._context = self._contexts[0]
        self._fixed_param_names = set(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._execs = []
        self._optimizer = None
        self._updater = None
        self._kvstore = None

    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def output_shapes(self):
        return [(n, o.shape) for n, o in
                zip(self.output_names, self._exec.outputs)]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):  # noqa: ARG002
        if self.binded and not force_rebind:
            return
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        n_ctx = len(self._contexts)
        shapes = {}
        for desc in list(data_shapes) + list(label_shapes or []):
            name, shape = desc[0], desc[1]
            shapes[name] = shape
        if n_ctx > 1:
            # data parallelism across ctxs: one executor per context, each
            # on an even batch slice (reference module/executor_group.py ::
            # DataParallelExecutorGroup)
            for name, shape in shapes.items():
                if shape[0] % n_ctx:
                    raise MXNetError(
                        f"batch dim of {name!r} ({shape[0]}) must divide "
                        f"evenly over {n_ctx} contexts (reference splits by "
                        "workload; even split here)")
            sliced = {n: (s[0] // n_ctx,) + tuple(s[1:])
                      for n, s in shapes.items()}
            self._execs = [self._symbol.simple_bind(
                ctx=c, grad_req=grad_req if for_training else "null",
                **sliced) for c in self._contexts]
        else:
            self._execs = [self._symbol.simple_bind(
                ctx=self._context,
                grad_req=grad_req if for_training else "null", **shapes)]
        self._exec = self._execs[0]
        self.binded = True
        self.for_training = for_training

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):  # noqa: ARG002
        if self.params_initialized and not force_init:
            return
        initializer = initializer or _init.Uniform(0.01)
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params and name in arg_params:
                arr._set_data(arg_params[name]._data)
            else:
                initializer(_init.InitDesc(name), arr)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params and name in aux_params:
                arr._set_data(aux_params[name]._data)
            else:
                initializer(_init.InitDesc(name), arr)
        self._broadcast_params()
        self.params_initialized = True

    def _broadcast_params(self):
        """Replicate exec0's params/aux to every other context's executor
        (reference executor_group param sync)."""
        for e in self._execs[1:]:
            for name in self._param_names:
                e.arg_dict[name]._set_data(
                    self._exec.arg_dict[name].as_in_context(
                        e.arg_dict[name].ctx)._data)
            for name in self._aux_names:
                e.aux_dict[name]._set_data(
                    self._exec.aux_dict[name].as_in_context(
                        e.aux_dict[name].ctx)._data)

    def get_params(self):
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init,
                         allow_extra=allow_extra)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):  # noqa: ARG002
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            optimizer = _opt.create(optimizer, **dict(optimizer_params))
        self._optimizer = optimizer
        self._updater = _opt.get_updater(optimizer)
        self.optimizer_initialized = True

    def _slice_for(self, arr, k):
        """k-th even batch slice of arr, on the k-th context.  Data always
        lands on the executor's context — iterators hand out host (cpu)
        arrays, and a tpu-bound module must not feed cpu buffers into its
        compiled graph (reference: DataParallelExecutorGroup copies slices
        to each ctx)."""
        n = len(self._execs)
        if n == 1:
            return arr.as_in_context(self._contexts[0])
        per = arr.shape[0] // n
        return arr[k * per:(k + 1) * per].as_in_context(self._contexts[k])

    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        for k, e in enumerate(self._execs):
            feed = {}
            for name, arr in zip(self._data_names, data_batch.data):
                feed[name] = self._slice_for(arr, k)
            if data_batch.label is not None:
                for name, arr in zip(self._label_names, data_batch.label):
                    if name in e.arg_dict:
                        feed[name] = self._slice_for(arr, k)
            e.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        for k, e in enumerate(self._execs):
            if out_grads is None:
                e.backward(None)
            else:
                ogs = out_grads if isinstance(out_grads, (list, tuple)) \
                    else [out_grads]
                e.backward([self._slice_for(g, k) for g in ogs])

    def update(self):
        for i, name in enumerate(self._param_names):
            if name in self._fixed_param_names:
                continue
            g = self._exec.grad_dict.get(name)
            if g is None:
                continue
            if len(self._execs) > 1:
                # sum grads across ctx replicas (DataParallelExecutorGroup
                # grad aggregation), update once, broadcast the result
                for e in self._execs[1:]:
                    g = g + e.grad_dict[name].as_in_context(g.ctx)
            self._updater(i, g, self._exec.arg_dict[name])
        if len(self._execs) > 1:
            # aux states (BN running stats) were updated per slice: average
            # them onto exec0 before the broadcast, else slice 0's stats
            # silently win (reference executor_group merges aux across ctxs)
            for name in self._aux_names:
                acc = self._exec.aux_dict[name]
                for e in self._execs[1:]:
                    acc = acc + e.aux_dict[name].as_in_context(acc.ctx)
                self._exec.aux_dict[name]._set_data(
                    (acc / len(self._execs))._data)
            self._broadcast_params()

    def get_outputs(self, merge_multi_context=True):
        if len(self._execs) == 1:
            return self._exec.outputs
        if not merge_multi_context:
            # reference contract: grouped per OUTPUT, inner list per ctx
            return [[e.outputs[i] for e in self._execs]
                    for i in range(len(self._exec.outputs))]
        merged = []
        for i in range(len(self._exec.outputs)):
            parts = [e.outputs[i].as_in_context(self._context)
                     for e in self._execs]
            merged.append(nd_concat_batch(parts))
        return merged

    def get_input_grads(self, merge_multi_context=True):
        if len(self._execs) == 1:
            return [self._exec.grad_dict.get(n) for n in self._data_names]
        if not merge_multi_context:
            return [[e.grad_dict.get(n) for e in self._execs]
                    for n in self._data_names]
        merged = []
        for n in self._data_names:
            parts = [e.grad_dict.get(n) for e in self._execs]
            if any(p is None for p in parts):
                merged.append(None)
                continue
            merged.append(nd_concat_batch(
                [p.as_in_context(self._context) for p in parts]))
        return merged

    def update_metric(self, eval_metric, labels, pre_sliced=False):  # noqa: ARG002
        eval_metric.update(labels, self.get_outputs())

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states and self._updater is not None:
            with open(f"{prefix}-{epoch:04d}.states", "wb") as f:
                f.write(self._updater.get_states())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint
        sym, arg, aux = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded_params = (arg, aux)
        mod._preload_opt_states = f"{prefix}-{epoch:04d}.states" \
            if load_optimizer_states else None
        return mod
