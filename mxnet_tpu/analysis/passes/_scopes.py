"""Designated scopes the graftcheck passes key on.

One committed registry of WHERE each rule applies: the hot-path purity
scope (GC01/GC05), the flag-discipline module set (GC05), and the
threaded-module prefixes (GC04/GC06/GC10).  Passes import these instead
of hard-coding paths so adding a module to a scope is one reviewable
diff line.
"""

from __future__ import annotations

import ast

# --------------------------------------------------------------------------
# designated scopes
# --------------------------------------------------------------------------

# Hot-path purity scope (GC01/GC05): module rel-path -> function names, or
# None meaning every function in the module is hot.
HOT_PATHS = {
    "ops/registry.py": {"invoke", "invoke_arrays", "_apply_cast",
                        "_callable_for", "_build_callable", "_normalize_out"},
    "kvstore/fusion.py": None,
    "kvstore/local.py": {"_reduce", "_reduce_rowsparse", "_store_merged",
                         "push", "pull", "pushpull", "pushpull_list",
                         "_fused_pushpull", "pushpull_flat",
                         "_split_fusable", "_stage_bucket"},
    "gluon/trainer.py": {"step", "_allreduce_grads", "_allreduce_grads_impl",
                         "_update", "_update_impl", "_update_aggregated",
                         "_update_fused", "_fused_kind"},
    "optimizer_fusion.py": None,
    # serving hot path: the per-iteration scheduler core and everything
    # inside the jitted decode trace (models.py raw bodies + the paged
    # attention kernel) must stay host-sync-free
    "serving/engine.py": {"step", "_admit", "_admit_one", "_ensure_blocks",
                          "_emit", "_req_finished", "_finish", "_preempt",
                          "_spec_step", "_spec_budgets", "_upload_tables",
                          "_sync_prefix_counters"},
    "serving/models.py": None,
    # prefix-cache bookkeeping (ISSUE 15): match/admit/prepare_write/
    # ensure_capacity run on every admission and scheduler iteration
    "serving/cache.py": None,
    "kernels/paged_attention.py": None,
    # io decode pipeline (ISSUE 7): the per-batch scheduler/collector core
    # and the worker decode body are the input-bound hot path
    "io/pipeline.py": {"next_batch", "_assemble_loop", "_collect", "_pump",
                       "_issue", "_inline_chunk", "_decode_chunk",
                       "_read_payload", "_attach_slab"},
    # sharding engine (ISSUE 8): rule matching/resolution runs at trace
    # time but sits on the TrainStep dispatch path, and the per-step
    # __call__/run bodies must stay host-sync-free
    "sharding.py": None,
    "parallel.py": {"__call__", "run", "_param_sharding",
                    "_shardings", "_data_shardings", "_build",
                    "_build_multi"},
    # observability plane (ISSUE 10): the StepClock feeds from the
    # trainer/TrainStep step path and counter shipping rides the decode
    # ack channel — both must stay host-sync-free and flag-disciplined
    "telemetry/stepclock.py": {"begin_step", "note", "end_step"},
    "telemetry/aggregate.py": {"counter_deltas", "absorb_counter_deltas"},
    # analytic observatory (ISSUE 12): the jit-boundary wrapper sits on
    # every instrumented dispatch (op dispatch included when armed) and
    # the scrape handler runs per request on server threads — both must
    # stay host-sync-free and flag-disciplined
    "telemetry/costmodel.py": {"__call__", "_probe", "wrap_jit",
                               "wrap_jit_if_armed", "_on_duration_event"},
    "telemetry/httpd.py": {"do_GET"},
    # perf-regression gate (ISSUE 16): the steady-state capture window is
    # the measured region of every snapshot lane — a host sync inside it
    # would serialize the dispatches it is counting (lane warmup/drain
    # syncs deliberately sit OUTSIDE these functions)
    "telemetry/perfgate.py": {"_steady_capture", "_metric_value",
                              "_site_rollup"},
    # elastic control plane (ISSUE 11): the controller's monitor loop
    # polls several times a second and the heartbeat note sits on the
    # worker's step path — both must stay host-sync-free and
    # flag-disciplined
    "resilience/controller.py": {"_watch_loop", "_poll_workers",
                                 "_read_heartbeats", "_check_hangs",
                                 "_check_straggler", "_manifest_latest"},
    "resilience/heartbeat.py": {"set_step", "beat", "_beater"},
    # serving router tier (ISSUE 13): the dispatch/ack/reader loops run
    # per request, the monitor polls several times a second, and the
    # replica's waiter/handler sit on every ack — all must stay
    # host-sync-free and flag-disciplined
    "serving/router.py": {"_dispatch_loop", "_dispatch_one",
                          "_pick_replica", "_send_to", "_on_ack",
                          "_reader_loop", "_monitor_loop", "_hedge_scan",
                          "_respawn_dead", "_check_heartbeats",
                          "_sweep_queued_deadlines", "_finish_req"},
    "serving/replica.py": {"_handle", "_waiter", "_send", "_load"},
}

# GC05 additionally audits these (they sit on the per-batch/per-call path
# even though they are not purity-critical).
FLAG_DISCIPLINE_MODULES = set(HOT_PATHS) | {
    "gluon/data/dataloader.py", "kvstore/dist.py",
}

# Threaded modules (GC04): rel-path prefixes.  These own locks or run user
# code on worker threads.
THREADED_MODULES = (
    "engine.py", "native.py", "profiler.py", "checkpoint.py",
    "ops/registry.py", "telemetry/", "resilience/",
    "gluon/data/dataloader.py", "kvstore/sparse_ps.py", "serving/",
    "io/pipeline.py",
)


def _dotted(expr):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def _is_threaded(rel):
    return any(rel == t or (t.endswith("/") and rel.startswith(t))
               for t in THREADED_MODULES)


def _walk_shallow(fn):
    """Yield nodes of ``fn``'s body without descending into nested
    function definitions (those are analyzed as their own scopes)."""
    stack = [fn]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _hot_functions(module):
    """Yield (qualname, FunctionDef) for every designated hot function in
    the module (nested defs inside a hot function are hot too)."""
    spec = HOT_PATHS.get(module.rel)
    if module.rel not in HOT_PATHS:
        return

    def walk(node, prefix, inside_hot):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                hot = inside_hot or spec is None or child.name in spec
                if hot:
                    yield qual, child
                yield from walk(child, qual + ".", hot)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.", inside_hot)

    yield from walk(module.tree, "", False)


