"""graftcheck passes — the ten committed rules, grouped by concern.

- :mod:`._scopes`   — the designated-scope tables the rules key on
                      (HOT_PATHS, FLAG_DISCIPLINE_MODULES,
                      THREADED_MODULES)
- :mod:`.purity`    — GC01 host-sync, GC02 retrace-hazard, GC03 knob
                      hygiene, GC04 lock discipline, GC05 telemetry
                      flags (the original intraprocedural five)
- :mod:`.concurrency` — GC06 lock-order cycles, GC07 use-after-donate,
                      GC10 thread lifecycle (interprocedural, built on
                      :class:`..core.ProjectIndex`)
- :mod:`.protocol`  — GC08 atomic-protocol writes, GC09 registry drift

Importing this package registers every pass with ``core.PASSES``;
``tools/graftcheck.py`` and :func:`..core.analyze_paths` rely on that
side effect.  Keep the registry sorted by rule id so ``--list-rules``
and the stats table read in order regardless of import sequence.
"""

from __future__ import annotations

from .. import core as _core
from . import concurrency, protocol, purity  # noqa: F401  (registration)
from ._scopes import (FLAG_DISCIPLINE_MODULES, HOT_PATHS,  # noqa: F401
                      THREADED_MODULES)
from .concurrency import LOCK_BASELINE_FILE  # noqa: F401
from .protocol import PROTOCOL_TOKENS  # noqa: F401

_core.PASSES.sort(key=lambda p: p.rule)

__all__ = [
    "HOT_PATHS",
    "FLAG_DISCIPLINE_MODULES",
    "THREADED_MODULES",
    "LOCK_BASELINE_FILE",
    "PROTOCOL_TOKENS",
]
