"""graftcheck rules GC08/GC09 — cross-process protocol discipline.

- **GC08 atomic-protocol writes**: eight modules hand-roll the same
  write-temp-then-``os.replace`` idiom because another *process* reads
  the file while it is being written (the controller reads heartbeats
  mid-beat, routers read port files mid-publish, the perf gate reads
  telemetry shards mid-export).  A direct ``open(path, 'w')`` against
  one of these protocol files can be observed torn — half a JSON object —
  and every reader's "torn = absent" recovery story silently degrades
  into "torn = crash".  The registry of protocol file tokens is
  committed here (:data:`PROTOCOL_TOKENS`); any write-mode open whose
  resolved path carries one must have an ``os.replace`` reachable from
  the same function (directly or through its callees).
- **GC09 registry drift**: string registries rot without a checker.
  Every ``chaos.hit(site)`` literal must exist in the committed
  ``chaos.SITES`` tuple *and* be armed by at least one test (an
  injection site no test fires is dead coverage).  Every metric name
  handed to the telemetry factories must follow the
  ``mxnet_*_{total,seconds,bytes,tokens}`` convention and appear in the
  README exposition docs, so dashboards never chase a renamed series.
"""

from __future__ import annotations

import ast
import os
import re

from ..core import Pass, call_leaf, dotted_chain, register_pass

# --------------------------------------------------------------------------
# GC08 — atomic-protocol write discipline
# --------------------------------------------------------------------------

# The committed registry of cross-process protocol file tokens: a write-
# open whose path expression resolves to a string literal *containing* a
# token is a protocol write.  Containment is one-directional on purpose —
# matching literal-inside-token too would let a one-character f-string
# fragment like "-" claim every entry here.
PROTOCOL_TOKENS = {
    "router.json": "serving router journal (Router._save_state, read by "
                   "_recover and operators mid-run)",
    "controller.json": "elastic controller state (Controller._save_state, "
                       "read by auto_resume and status tooling mid-run)",
    "manifest.json": "checkpoint manifest (checkpoint.save, read by the "
                     "controller's regrow watcher mid-save)",
    "hb-rank": "heartbeat records (heartbeat.beat, read by the "
               "controller's hang detector several times a second)",
    "replica-": "replica port files (replica.bind, read by the router's "
                "connect/respawn path)",
    "telemetry-": "telemetry snapshot shards (aggregate.export_snapshot, "
                  "read by the controller roll-up and perf gate)",
}

_WRITE_MODE_RE = re.compile(r"[wx]")


def _is_protocol_token(tok):
    return any(p in tok for p in PROTOCOL_TOKENS)


@register_pass
class AtomicProtocolPass(Pass):
    rule = "GC08"
    summary = ("atomic-protocol discipline: writes to cross-process "
               "protocol files (router.json, controller.json, "
               "manifest.json, heartbeats, port files, telemetry shards) "
               "must flow through write-temp-then-os.replace; a direct "
               "open(path, 'w') can be read torn")

    def check_project(self, ctx):
        idx = ctx.index
        out = []
        for m in ctx.modules:
            for fi in sorted(idx.functions_in(m), key=lambda f: f.qual):
                s = idx.summary(fi)
                protocol_writes = []
                for mode, call, line in s.opens:
                    if not _WRITE_MODE_RE.search(mode):
                        continue   # reads and append-only logs are fine
                    toks = idx.expr_tokens(fi, call.args[0])
                    hits = sorted(t for t in toks if _is_protocol_token(t))
                    if hits:
                        protocol_writes.append((call, line, hits))
                if not protocol_writes:
                    continue
                if self._replace_reachable(idx, fi):
                    continue   # the function implements the atomic idiom
                for call, line, hits in protocol_writes:
                    tok = next(p for p in PROTOCOL_TOKENS
                               if any(p in t for t in hits))
                    out.append(m.finding(
                        self.rule, line,
                        f"direct write to protocol file ({hits[0]!r}: "
                        f"{PROTOCOL_TOKENS[tok]}) with no os.replace "
                        "reachable from this function — a concurrent "
                        "reader can observe a torn file; write to a tmp "
                        "path and os.replace() it into place"))
        return out

    @staticmethod
    def _replace_reachable(idx, fi, _depth=0, _seen=None):
        """True when an ``os.replace``/``os.rename`` is reachable from
        ``fi`` through resolvable calls (3 hops)."""
        if _seen is None:
            _seen = set()
        if fi.key in _seen or _depth > 3:
            return False
        _seen.add(fi.key)
        s = idx.summary(fi)
        if s.replaces:
            return True
        for call in s.calls:
            g = idx.resolve_call(fi.module, fi, call)
            if g is not None and AtomicProtocolPass._replace_reachable(
                    idx, g, _depth + 1, _seen):
                return True
        return False


# --------------------------------------------------------------------------
# GC09 — registry drift (chaos sites, metric names)
# --------------------------------------------------------------------------

_CHAOS_MODULE = "resilience/chaos.py"
_METRIC_FACTORIES = {"counter": "_total",
                     "gauge": None,
                     "histogram": ("_seconds", "_bytes", "_tokens")}
_METRIC_NAME_RE = re.compile(r"^mxnet_[a-z0-9_]+$")


def _sites_registry(chaos_module):
    """{site: lineno} parsed from the module-level SITES tuple."""
    for node in chaos_module.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "SITES"
                and isinstance(node.value, (ast.Tuple, ast.List))):
            return {e.value: e.lineno for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
    return None


@register_pass
class RegistryDriftPass(Pass):
    rule = "GC09"
    summary = ("registry drift: chaos.hit sites must exist in chaos.SITES "
               "and be armed by a test; metric names must match "
               "mxnet_*_{total,seconds,bytes,tokens} and appear in the "
               "README exposition docs")

    def check_project(self, ctx):
        out = []
        out.extend(self._check_chaos(ctx))
        out.extend(self._check_metrics(ctx))
        return out

    # -- chaos sites ----------------------------------------------------------

    def _check_chaos(self, ctx):
        chaos = ctx.module(_CHAOS_MODULE)
        if chaos is None:
            return []
        sites = _sites_registry(chaos)
        if sites is None:
            if not ctx.repo_root:
                return []   # synthetic check_source module, not the tree
            return [chaos.finding(
                self.rule, 1,
                "chaos module has no parseable module-level SITES tuple — "
                "the injection-site registry must stay statically "
                "checkable")]
        idx = ctx.index
        out = []
        for m in ctx.modules:
            imports = idx.mod_imports.get(m.rel, {})
            for node in ast.walk(m.tree):
                if not (isinstance(node, ast.Call)
                        and call_leaf(node) == "hit" and node.args):
                    continue
                recv = (dotted_chain(node.func.value)
                        if isinstance(node.func, ast.Attribute) else None)
                is_chaos = (
                    m.rel == _CHAOS_MODULE
                    or (recv is not None and imports.get("modules", {})
                        .get(recv) == _CHAOS_MODULE)
                    or (recv is None and imports.get("symbols", {})
                        .get("hit", (None,))[0] == _CHAOS_MODULE))
                if not is_chaos:
                    continue
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    out.append(m.finding(
                        self.rule, node,
                        "chaos.hit() with a non-literal site — sites must "
                        "be string literals so the registry stays "
                        "statically checkable"))
                    continue
                if arg.value not in sites:
                    out.append(m.finding(
                        self.rule, node,
                        f"chaos.hit site {arg.value!r} is not in the "
                        "committed chaos.SITES registry — register it "
                        "(and arm it in a test) or fix the typo"))
        # every registered site must be armed by at least one test
        tests_dir = (os.path.join(ctx.repo_root, "tests")
                     if ctx.repo_root else None)
        if tests_dir and os.path.isdir(tests_dir):
            blob = []
            for fn in sorted(os.listdir(tests_dir)):
                if fn.endswith(".py"):
                    try:
                        with open(os.path.join(tests_dir, fn),
                                  encoding="utf-8") as f:
                            blob.append(f.read())
                    except OSError:
                        pass
            blob = "\n".join(blob)
            for site, lineno in sorted(sites.items()):
                if site not in blob:
                    out.append(chaos.finding(
                        self.rule, lineno,
                        f"chaos site {site!r} is registered but no test "
                        "references it — dead injection coverage; arm it "
                        "in a test or retire the site"))
        return out

    # -- metric names -----------------------------------------------------------

    def _check_metrics(self, ctx):
        out = []
        readme = ctx.read_repo_file("README.md") if ctx.repo_root else None
        for m in ctx.modules:
            if m.rel.startswith("analysis/"):
                continue
            for node in ast.walk(m.tree):
                if not (isinstance(node, ast.Call) and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                kind = call_leaf(node)
                if kind not in _METRIC_FACTORIES:
                    continue
                name = node.args[0].value
                if not name.startswith("mxnet_"):
                    continue   # not a telemetry metric registration
                if not _METRIC_NAME_RE.match(name):
                    out.append(m.finding(
                        self.rule, node,
                        f"metric name {name!r} breaks the "
                        "mxnet_[a-z0-9_]+ convention"))
                    continue
                suffix = _METRIC_FACTORIES[kind]
                if kind == "counter" and not name.endswith("_total"):
                    out.append(m.finding(
                        self.rule, node,
                        f"counter {name!r} must end in '_total' "
                        "(prometheus counter convention)"))
                elif kind == "histogram" and not name.endswith(suffix):
                    out.append(m.finding(
                        self.rule, node,
                        f"histogram {name!r} must end in one of "
                        f"{'/'.join(suffix)} (unit-suffix convention)"))
                elif kind == "gauge" and name.endswith("_total"):
                    # _seconds is a fine gauge unit suffix (ages, budgets
                    # — cf. prometheus' own process_start_time_seconds);
                    # _total is a counter contract and nothing else.
                    out.append(m.finding(
                        self.rule, node,
                        f"gauge {name!r} ends in '_total' — that suffix "
                        "promises a monotone counter; rename or use a "
                        "counter"))
                elif readme is not None and name not in readme:
                    out.append(m.finding(
                        self.rule, node,
                        f"metric {name!r} is exported but undocumented — "
                        "add it to the README metrics exposition table"))
        return out
