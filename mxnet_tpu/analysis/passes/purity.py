"""graftcheck rules GC01–GC05 — purity, retrace, knob, lock, flag.

Each pass encodes an invariant the runtime subsystems (telemetry PR 1,
gradient fusion PR 2, resilience PR 3) depend on but nothing previously
enforced:

- **GC01 host-sync**: the dispatch/fusion hot path must not silently sync
  device → host.  Flags ``.item()`` / ``.asnumpy()`` /
  ``.block_until_ready()`` / ``waitall()`` / ``jax.device_get`` anywhere
  in a designated hot-path function, and ``float()/int()/bool()/len()`` /
  ``np.asarray`` applied to traced/jax values (tracked by a small local
  dataflow over ``._data`` / ``jnp.*`` producers).
- **GC02 retrace-hazard**: functions handed to ``jax.jit`` must not close
  over mutable state (``self``, rebindable module globals, reassigned
  enclosing locals) — stale values get baked into cached traces; and jit
  results must be cached, not built per call.  Mutable-literal defaults
  and untyped ``**kwargs`` reaching a trace (bypassing ``_freeze`` /
  ``static_argnames``) are the quiet version of the same bug.
- **GC03 knob-hygiene**: every ``MXNET_*`` env read outside ``config.py``
  is ungoverned (no default, no type, no docs); every knob registered in
  ``config.KNOWN_VARS`` must appear in the README knob table.
- **GC04 lock-discipline**: in the threaded modules, an attribute or
  module global written under ``with <lock>`` in one function and
  written lock-free in another is a data race waiting for a scheduler.
- **GC05 telemetry-flag discipline**: hot-path functions read the
  telemetry-enabled flag at most once (snapshot it; re-reads both waste
  cycles and can observe a mid-call flip, tearing paired begin/end
  instrumentation).
"""

from __future__ import annotations

import ast
import symtable

from ..core import Pass, register_pass
from ._scopes import (FLAG_DISCIPLINE_MODULES, HOT_PATHS,  # noqa: F401
                      _dotted, _hot_functions, _is_threaded, _walk_shallow)

# --------------------------------------------------------------------------
# GC01 — host-sync on the hot path
# --------------------------------------------------------------------------

_SYNC_METHODS = {"item", "asnumpy", "block_until_ready"}
_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
_NUMPY_ROOTS = {"np", "_np", "numpy", "onp"}
_JAX_PRODUCER_ROOTS = {"jnp", "lax"}
_CAST_BUILTINS = {"float", "int", "bool", "len"}


def _expr_arrayish(expr, names):
    """Syntactic 'holds a jax/traced array' judgment."""
    if isinstance(expr, ast.Name):
        return expr.id in names
    if isinstance(expr, ast.Attribute):
        if expr.attr in ("_data", "_grad"):
            return True
        return False
    if isinstance(expr, ast.Subscript):
        return _expr_arrayish(expr.value, names)
    if isinstance(expr, ast.BinOp):
        return (_expr_arrayish(expr.left, names)
                or _expr_arrayish(expr.right, names))
    if isinstance(expr, ast.UnaryOp):
        return _expr_arrayish(expr.operand, names)
    if isinstance(expr, ast.Call):
        d = _dotted(expr.func)
        if d:
            root = d.split(".")[0]
            if root in _JAX_PRODUCER_ROOTS:
                return True
            if root == "jax" and d not in ("jax.jit",):
                return True
            if d == "tree_sum" or d.endswith(".tree_sum"):
                return True
        # method on an arrayish object returns arrayish (e.g. x.reshape)
        if isinstance(expr.func, ast.Attribute):
            return _expr_arrayish(expr.func.value, names)
    return False


@register_pass
class HostSyncPass(Pass):
    rule = "GC01"
    summary = ("host-sync on the hot path: .item()/.asnumpy()/waitall()/"
               "device_get, or float/int/bool/len/np.asarray on a traced "
               "value, inside a designated hot-path function")

    def check_module(self, module, ctx):
        out = []
        for qual, fn in _hot_functions(module):
            out.extend(self._check_function(module, qual, fn))
        return out

    def _check_function(self, module, qual, fn):
        out = []
        nodes = list(_walk_shallow(fn))
        # dataflow to fixpoint: x = <arrayish expr> tags x (iterated so
        # traversal order doesn't matter)
        arrayish = set()
        changed = True
        while changed:
            changed = False
            for node in nodes:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id not in arrayish \
                        and _expr_arrayish(node.value, arrayish):
                    arrayish.add(node.targets[0].id)
                    changed = True
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SYNC_METHODS and not node.args:
                out.append(module.finding(
                    self.rule, node,
                    f"host-sync: .{node.func.attr}() in hot path "
                    f"{qual!r} blocks dispatch until the device flushes"))
                continue
            if d in _SYNC_CALLS or (d and d.split(".")[-1] == "waitall"):
                out.append(module.finding(
                    self.rule, node,
                    f"host-sync: {d}() in hot path {qual!r} drains the "
                    "async dispatch queue"))
                continue
            if d and "." in d and d.split(".")[0] in _NUMPY_ROOTS \
                    and d.split(".")[-1] in ("asarray", "array") \
                    and node.args \
                    and _expr_arrayish(node.args[0], arrayish):
                out.append(module.finding(
                    self.rule, node,
                    f"host-sync: {d}() on a traced/jax value in hot path "
                    f"{qual!r} copies device memory to host"))
                continue
            if d in _CAST_BUILTINS and len(node.args) == 1 \
                    and _expr_arrayish(node.args[0], arrayish):
                out.append(module.finding(
                    self.rule, node,
                    f"host-sync: {d}() on a traced/jax value in hot path "
                    f"{qual!r} forces a device->host transfer (and fails "
                    "under trace)"))
        return out


# --------------------------------------------------------------------------
# GC02 — retrace hazards
# --------------------------------------------------------------------------


class _Scope:
    __slots__ = ("node", "parent", "defs", "bindings", "mutated",
                 "globals_declared")

    def __init__(self, node, parent):
        self.node = node
        self.parent = parent
        self.defs = {}          # name -> FunctionDef/Lambda node
        self.bindings = {}      # name -> count of binding sites
        self.mutated = set()    # names target of AugAssign
        self.globals_declared = set()

    def bind(self, name, n=1):
        self.bindings[name] = self.bindings.get(name, 0) + n


def _collect_scopes(tree):
    """Scope table: id(function node) -> _Scope, plus the module scope
    under key None.  Bindings are counted per scope (params, assignments,
    defs, imports); AugAssign marks a name mutated."""
    scopes = {}

    def bind_target(scope, tgt):
        if isinstance(tgt, ast.Name):
            scope.bind(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                bind_target(scope, e)
        elif isinstance(tgt, ast.Starred):
            bind_target(scope, tgt.value)

    def visit_body(scope, body):
        for node in body:
            visit(scope, node)

    def visit(scope, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope.defs[node.name] = node
            scope.bind(node.name)
            sub = _Scope(node, scope)
            scopes[id(node)] = sub
            a = node.args
            for p in (a.posonlyargs + a.args + a.kwonlyargs):
                sub.bind(p.arg)
            for p in (a.vararg, a.kwarg):
                if p is not None:
                    sub.bind(p.arg)
            # defaults/decorators evaluate in the parent scope
            for d in list(a.defaults) + [x for x in a.kw_defaults if x] \
                    + list(node.decorator_list):
                visit(scope, d)
            visit_body(sub, node.body)
            return
        if isinstance(node, ast.Lambda):
            sub = _Scope(node, scope)
            scopes[id(node)] = sub
            a = node.args
            for p in (a.posonlyargs + a.args + a.kwonlyargs):
                sub.bind(p.arg)
            for p in (a.vararg, a.kwarg):
                if p is not None:
                    sub.bind(p.arg)
            visit(sub, node.body)
            return
        if isinstance(node, ast.ClassDef):
            scope.bind(node.name)
            # class body binds in its own namespace; methods' enclosing
            # *function* scope chain skips it, so hang methods off the
            # current scope for resolution purposes
            visit_body(scope, node.body)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                bind_target(scope, t)
        elif isinstance(node, ast.AnnAssign) and node.target is not None:
            bind_target(scope, node.target)
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name):
                scope.bind(node.target.id)
                scope.mutated.add(node.target.id)
        elif isinstance(node, ast.NamedExpr):
            bind_target(scope, node.target)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            bind_target(scope, node.target)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                scope.bind((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, ast.Global):
            scope.globals_declared.update(node.names)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            scope.bind(node.name)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    bind_target(scope, item.optional_vars)
        for child in ast.iter_child_nodes(node):
            visit(scope, child)

    mod = _Scope(None, None)
    scopes[None] = mod
    visit_body(mod, tree.body)
    return scopes


def _mutable_globals(scopes):
    """Module-level names that are rebound after their first binding:
    assigned ≥2 times at module scope, augmented, or assigned inside any
    function that declares them ``global``."""
    mod = scopes[None]
    out = {n for n, c in mod.bindings.items() if c >= 2}
    out |= mod.mutated
    for s in scopes.values():
        if s is mod or s is None:
            continue
        for n in s.globals_declared:
            if s.bindings.get(n):
                out.add(n)
    return out


def _symtable_index(text, path):
    """(name, lineno) -> symtable entry for every function scope; None on
    any symtable failure (the pass then skips free/global analysis)."""
    try:
        top = symtable.symtable(text, path, "exec")
    except (SyntaxError, ValueError):
        return None
    index = {}

    def walk(tb):
        for child in tb.get_children():
            if child.get_type() == "function":
                index.setdefault((child.get_name(), child.get_lineno()),
                                 child)
            walk(child)

    walk(top)
    return index


_JIT_SAFE_KWARGS = {
    "static_argnums", "static_argnames", "donate_argnums", "donate_argnames",
    "in_shardings", "out_shardings", "device", "backend", "keep_unused",
    "inline", "abstracted_axes",
}


@register_pass
class RetraceHazardPass(Pass):
    rule = "GC02"
    summary = ("retrace hazard: jitted closure captures mutable state "
               "(self / rebindable global / reassigned local), jit built "
               "per call, mutable-literal defaults, or **kwargs reaching "
               "a trace without static_argnames/_freeze")

    def check_module(self, module, ctx):
        scopes = _collect_scopes(module.tree)
        mutable_globals = _mutable_globals(scopes)
        st_index = _symtable_index(module.text, module.path)
        out = []

        def resolve(name, scope):
            s = scope
            while s is not None:
                if name in s.defs:
                    return s.defs[name], s
                s = s.parent
            return None, None

        def walk(node, scope):
            for child in ast.iter_child_nodes(node):
                sub = scopes.get(id(child))
                if isinstance(child, ast.Call):
                    self._check_call(module, child, scope, scopes,
                                     mutable_globals, st_index, resolve, out)
                walk(child, sub if sub is not None else scope)

        walk(module.tree, scopes[None])
        return out

    @staticmethod
    def _is_jit(func):
        d = _dotted(func)
        return d in ("jax.jit", "jit", "pjit", "jax.pjit")

    def _check_call(self, module, call, scope, scopes, mutable_globals,
                    st_index, resolve, out):
        # jax.jit(f)(...) — a fresh compile every execution
        if isinstance(call.func, ast.Call) and self._is_jit(call.func.func):
            out.append(module.finding(
                self.rule, call,
                "jax.jit(...) built and invoked in one expression — the "
                "executable is rebuilt (and retraced) on every call; cache "
                "it keyed on shape/dtype/static attrs"))
            return
        if not self._is_jit(call.func):
            return
        target = call.args[0] if call.args else None
        fnode = None
        if isinstance(target, ast.Lambda):
            fnode = target
        elif isinstance(target, ast.Name):
            fnode, _def_scope = resolve(target.id, scope)
        if fnode is None:
            return  # call-expression target: not statically resolvable

        # (c) mutable-literal defaults are baked into the trace object
        # identity — they bypass any _freeze()-style cache key
        if not isinstance(fnode, ast.Lambda) or fnode.args.defaults:
            a = fnode.args
            for dflt in list(a.defaults) + [x for x in a.kw_defaults if x]:
                if isinstance(dflt, (ast.Dict, ast.List, ast.Set,
                                     ast.DictComp, ast.ListComp,
                                     ast.SetComp)):
                    out.append(module.finding(
                        self.rule, call,
                        "jitted function has a mutable-literal default — "
                        "its contents are baked into the first trace and "
                        "never revalidated; freeze it into the jit cache "
                        "key instead"))
                    break

        # (d) **kwargs reaching the trace untyped
        if getattr(fnode.args, "kwarg", None) is not None:
            kw_names = {k.arg for k in call.keywords}
            if not (kw_names & {"static_argnames", "static_argnums"}):
                out.append(module.finding(
                    self.rule, call,
                    "jitted function takes **kwargs with no "
                    "static_argnames/static_argnums — non-array kwargs "
                    "bypass _freeze and either retrace per value or fail "
                    "to hash"))

        # (a)/(b) closure captures
        fscope = scopes.get(id(fnode))
        st = None
        if st_index is not None and not isinstance(fnode, ast.Lambda):
            st = st_index.get((fnode.name, fnode.lineno))
        if st is not None:
            frees = set(st.get_frees())
            globs = set(st.get_globals())
        else:
            frees, globs = self._approx_names(fnode, fscope)
        for name in sorted(frees):
            if name in ("self", "cls"):
                out.append(module.finding(
                    self.rule, call,
                    f"jitted closure captures {name!r} — instance state "
                    "read at trace time is baked into the cached "
                    "executable and goes stale silently"))
                continue
            bscope = fscope.parent if fscope else None
            while bscope is not None and not bscope.bindings.get(name):
                bscope = bscope.parent
            if bscope is not None and (
                    bscope.bindings.get(name, 0) >= 2
                    or name in bscope.mutated):
                out.append(module.finding(
                    self.rule, call,
                    f"jitted closure captures {name!r}, which is "
                    "reassigned in the enclosing scope — the trace keeps "
                    "the value from trace time, not call time; pass it as "
                    "an argument or bind it as a default"))
        for name in sorted(globs):
            if name in mutable_globals:
                out.append(module.finding(
                    self.rule, call,
                    f"jitted closure reads module global {name!r}, which "
                    "is rebound elsewhere — the cached trace freezes one "
                    "value forever; thread it through arguments or the "
                    "cache key"))

    @staticmethod
    def _approx_names(fnode, fscope):
        """Fallback free/global split when symtable indexing failed: every
        Load of a name not bound locally, attributed to 'free' if an
        enclosing function scope binds it, else 'global'."""
        loads = {n.id for n in ast.walk(fnode)
                 if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}
        local = set(fscope.bindings) if fscope else set()
        frees, globs = set(), set()
        for name in loads - local:
            s = fscope.parent if fscope else None
            hit = False
            while s is not None and s.node is not None:
                if s.bindings.get(name):
                    hit = True
                    break
                s = s.parent
            (frees if hit else globs).add(name)
        return frees, globs


# --------------------------------------------------------------------------
# GC03 — env-knob hygiene
# --------------------------------------------------------------------------


@register_pass
class KnobHygienePass(Pass):
    rule = "GC03"
    summary = ("knob hygiene: MXNET_* env reads outside config.py; knobs "
               "registered in config.KNOWN_VARS but missing from the "
               "README knob table")

    def check_module(self, module, ctx):
        if module.rel == "config.py":
            return []
        out = []
        for node in ast.walk(module.tree):
            knob, how = self._env_read(node)
            if knob and knob.startswith("MXNET_"):
                out.append(module.finding(
                    self.rule, node,
                    f"ungoverned env read {how}({knob!r}) — route it "
                    "through mxnet_tpu.config (register the knob in "
                    "KNOWN_VARS so it is typed, defaulted, and "
                    "documented)"))
        return out

    @staticmethod
    def _env_read(node):
        """(knob, 'os.environ.get'|...) when node reads a string-literal
        env var, else (None, None)."""
        def lit(e):
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                return e.value
            # computed names ("MXNET_X" if cond else "MXNET_Y",
            # "MXNET_" + suffix, f-strings): any embedded MXNET_* literal
            # marks the read as knob-shaped
            for sub in ast.walk(e):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str) \
                        and sub.value.startswith("MXNET_"):
                    return sub.value
            return None

        if isinstance(node, ast.Subscript):
            d = _dotted(node.value)
            if d and d.split(".")[-1] == "environ":
                return lit(node.slice), d + "[...]"
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if not d:
                return None, None
            leaf = d.split(".")[-1]
            if leaf == "getenv" and node.args:
                return lit(node.args[0]), d
            if leaf in ("get", "setdefault", "pop") \
                    and isinstance(node.func, ast.Attribute):
                base = _dotted(node.func.value)
                if base and base.split(".")[-1] == "environ" and node.args:
                    return lit(node.args[0]), d
        return None, None

    def check_project(self, ctx):
        cfg = ctx.module("config.py")
        if cfg is None:
            return []
        readme = ctx.read_repo_file("README.md")
        if readme is None:
            return []
        out = []
        for name, lineno in self._known_vars(cfg.tree):
            if name not in readme:
                out.append(cfg.finding(
                    self.rule, lineno,
                    f"knob {name} is registered in config.KNOWN_VARS but "
                    "undocumented — add it to the README env-knob table"))
        return out

    @staticmethod
    def _known_vars(tree):
        for node in tree.body:
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == "KNOWN_VARS"
                            for t in node.targets) \
                    and isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        yield k.value, k.lineno
                return


# --------------------------------------------------------------------------
# GC04 — lock discipline
# --------------------------------------------------------------------------


def _looks_like_lock(expr):
    d = _dotted(expr)
    if not d:
        return False
    leaf = d.split(".")[-1].lower()
    return "lock" in leaf or "mutex" in leaf


@register_pass
class LockDisciplinePass(Pass):
    rule = "GC04"
    summary = ("lock discipline: attribute/global written under a lock in "
               "one function of a threaded module but written lock-free "
               "in another")

    # functions whose writes construct the object / tear it down before or
    # after any concurrent access exists
    _EXEMPT = {"__init__", "__new__", "__init_subclass__"}

    def check_module(self, module, ctx):
        if not _is_threaded(module.rel):
            return []
        module_globals = self._module_level_names(module.tree)
        # key -> list of (funcname, locked, lineno); key is
        # ("self", class, attr) or ("global", name)
        writes = {}

        def record(key, func, locked, lineno):
            writes.setdefault(key, []).append((func, locked, lineno))

        def scan_function(fn, cls, qual):
            declared_global = {
                n for node in ast.walk(fn) if isinstance(node, ast.Global)
                for n in node.names}

            def key_for(target):
                base = target
                while isinstance(base, ast.Subscript):
                    base = base.value
                if isinstance(base, ast.Attribute) \
                        and isinstance(base.value, ast.Name) \
                        and base.value.id == "self":
                    return ("self", cls, base.attr)
                if isinstance(base, ast.Name):
                    if base.id in declared_global \
                            or (isinstance(target, ast.Subscript)
                                and base.id in module_globals):
                        return ("global", base.id)
                if isinstance(base, ast.Attribute) \
                        and isinstance(base.value, ast.Name) \
                        and base.value.id in module_globals \
                        and not isinstance(target, ast.Attribute):
                    # mutation through a module-global container attr
                    return ("global", base.value.id)
                return None

            def visit(node, locked):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)) and node is not fn:
                    return  # nested defs execute later, in their own calls
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    now = locked or any(
                        _looks_like_lock(item.context_expr.func
                                         if isinstance(item.context_expr,
                                                       ast.Call)
                                         else item.context_expr)
                        for item in node.items)
                    for item in node.items:
                        visit(item.context_expr, locked)
                    for st in node.body:
                        visit(st, now)
                    return
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AugAssign):
                    targets = [node.target]
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None:
                    targets = [node.target]
                for t in targets:
                    k = key_for(t)
                    if k is not None:
                        record(k, qual, locked, node.lineno)
                for child in ast.iter_child_nodes(node):
                    visit(child, locked)

            visit(fn, False)

        for node in module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name not in self._EXEMPT:
                    scan_function(node, None, node.name)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)) \
                            and sub.name not in self._EXEMPT:
                        scan_function(sub, node.name,
                                      f"{node.name}.{sub.name}")

        out = []
        for key, events in sorted(writes.items(), key=str):
            locked_funcs = {f for f, locked, _ in events if locked}
            if not locked_funcs:
                continue
            what = (f"self.{key[2]} (class {key[1]})" if key[0] == "self"
                    else f"module global {key[1]!r}")
            for func, locked, lineno in events:
                if not locked and func not in locked_funcs:
                    out.append(_mk_gc04(self.rule, key, what, func,
                                        locked_funcs, lineno, module))
        return out

    @staticmethod
    def _module_level_names(tree):
        names = set()
        for node in tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                names.add(node.target.id)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    names.add((alias.asname or alias.name).split(".")[0])
        return names


def _mk_gc04(rule, key, what, func, locked_funcs, lineno, module):
    holders = ", ".join(sorted(locked_funcs))
    return module.finding(
        rule, lineno,
        f"lock-free write to {what} in {func!r}, but {holders} write(s) "
        "it under a lock — take the same lock here or document why the "
        "race is benign")


# --------------------------------------------------------------------------
# GC05 — telemetry-flag discipline
# --------------------------------------------------------------------------


@register_pass
class TelemetryFlagPass(Pass):
    rule = "GC05"
    summary = ("telemetry-flag discipline: a hot-path function reads the "
               "telemetry-enabled flag more than once (snapshot it once; "
               "re-reads can observe a mid-call flip and tear paired "
               "instrumentation)")

    def check_module(self, module, ctx):
        if module.rel not in FLAG_DISCIPLINE_MODULES:
            return []
        out = []
        for qual, fn in self._functions(module):
            reads = []
            for node in _walk_shallow(fn):
                if isinstance(node, ast.Attribute) \
                        and node.attr == "_ENABLED" \
                        and isinstance(node.ctx, ast.Load):
                    reads.append(node)
                elif isinstance(node, ast.Name) and node.id == "_ENABLED" \
                        and isinstance(node.ctx, ast.Load):
                    reads.append(node)
                elif isinstance(node, ast.Call):
                    d = _dotted(node.func)
                    if d and d.split(".")[-1] == "enabled":
                        reads.append(node)
            reads.sort(key=lambda n: (n.lineno, n.col_offset))
            if len(reads) >= 2:
                out.append(module.finding(
                    self.rule, reads[1],
                    f"{qual!r} reads the telemetry-enabled flag "
                    f"{len(reads)} times — snapshot it once at entry "
                    "(enabled = tracer._ENABLED) and branch on the local"))
        return out

    @staticmethod
    def _functions(module):
        def walk(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    yield f"{prefix}{child.name}", child
                    # nested defs audited independently
                    yield from walk(child, f"{prefix}{child.name}.")
                elif isinstance(child, ast.ClassDef):
                    yield from walk(child, f"{prefix}{child.name}.")

        yield from walk(module.tree, "")
