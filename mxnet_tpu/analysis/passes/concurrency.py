"""graftcheck rules GC06/GC07/GC10 — interprocedural concurrency rules.

All three are thin rules over :class:`~..core.ProjectIndex` summaries:

- **GC06 lock-order**: every nested ``with lock:`` acquisition in the
  threaded modules — directly or through resolvable calls — contributes
  an edge to a project-wide lock-order graph.  A cycle is a potential
  deadlock (two threads entering it from different corners block each
  other forever) and is reported with a witness path per edge.  The
  acyclic edge set itself is *codified*: the committed
  ``graftcheck-lockorder.json`` at the repo root is the documented
  ordering, and any edge not in it (or stale in it) is a finding, so a
  PR that introduces a new ordering must update the baseline in the same
  diff — loudly, reviewably.
- **GC07 use-after-donate**: a buffer passed at a donated position of a
  ``donate_argnums`` jit is freed the moment dispatch begins; any later
  read of the same binding is a silent use-after-free (XLA may have
  already reused the pages).  The pass indexes every donating callable —
  direct ``jax.jit(f, donate_argnums=...)`` results, wrapper-transparent
  (``wrap_jit(jax.jit(...))``), builder-returned, bound to locals,
  module globals, or ``self`` attributes — and flags reads of donated
  bindings after the dispatch, including re-reads on the next iteration
  of an enclosing loop when the binding is never rebound.
- **GC10 thread-lifecycle**: a non-daemon thread that is never joined
  outlives shutdown and deadlocks interpreter exit; a ``while True``
  loop reachable from a thread target that neither reads a
  stop/shutdown-ish flag nor returns can never be told to exit.
"""

from __future__ import annotations

import ast
import json
import os
import re

from ..core import (Finding, Pass, call_leaf, dotted_chain, iter_own_nodes,
                    register_pass)
from ._scopes import _is_threaded

# --------------------------------------------------------------------------
# GC06 — lock-order cycles + committed edge baseline
# --------------------------------------------------------------------------

LOCK_BASELINE_FILE = "graftcheck-lockorder.json"


def _sccs(graph):
    """Tarjan strongly-connected components of {node: {succ}}."""
    index_of, low, on_stack = {}, {}, set()
    stack, out, counter = [], [], [0]

    def strong(v):
        index_of[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(graph.get(v, ())):
            if w not in index_of:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index_of[w])
        if low[v] == index_of[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            out.append(comp)

    for v in sorted(graph):
        if v not in index_of:
            strong(v)
    return out


def _cycle_in(scc, graph):
    """One concrete cycle (node list, last wraps to first) inside a
    non-trivial SCC, found by DFS from its smallest node."""
    start = min(scc)
    members = set(scc)
    path, seen = [start], {start}

    def dfs(v):
        for w in sorted(graph.get(v, ())):
            if w == start:
                return True
            if w in members and w not in seen:
                seen.add(w)
                path.append(w)
                if dfs(w):
                    return True
                path.pop()
        return False

    dfs(start)
    return path


@register_pass
class LockOrderPass(Pass):
    rule = "GC06"
    summary = ("lock-order: nested lock acquisitions (through calls) in "
               "the threaded modules must form a DAG matching the "
               "committed graftcheck-lockorder.json; cycles are potential "
               "deadlocks, unlisted/stale edges are drift")

    def edges(self, ctx):
        """{(from_id, to_id): {'module', 'line', 'witness'}} — the
        observed lock-order edge set with one witness each."""
        idx = ctx.index
        out = {}
        for m in ctx.modules:
            if not _is_threaded(m.rel):
                continue
            for fi in sorted(idx.functions_in(m), key=lambda f: f.qual):
                s = idx.summary(fi)
                for held, inner, hline, iline in s.pairs:
                    out.setdefault((held, inner), {
                        "module": m, "line": iline,
                        "witness": (f"{m.rel}::{fi.qual} holds {held} "
                                    f"(line {hline}) and acquires {inner} "
                                    f"(line {iline})")})
                for held, hline, call in s.region_calls:
                    g = idx.resolve_call(m, fi, call)
                    if g is None:
                        continue
                    for lid, (chain, site) in sorted(
                            idx.may_acquire(g).items()):
                        if lid == held or (held, lid) in out:
                            continue
                        hops = " -> ".join(
                            (f"{g.module.rel}::{g.qual}",) + chain)
                        out[(held, lid)] = {
                            "module": m, "line": call.lineno,
                            "witness": (f"{m.rel}::{fi.qual} holds {held} "
                                        f"(line {hline}) and calls {hops}, "
                                        f"which acquires {lid} at {site}")}
        return out

    def write_lock_baseline(self, path, ctx):
        edges = self.edges(ctx)
        data = {
            "comment": "graftcheck GC06 lock-order baseline — the "
                       "documented acquisition ordering; regenerate with "
                       "tools/graftcheck.py --write-lock-baseline after "
                       "reviewing any new edge for cycles",
            "edges": [{"from": a, "to": b, "witness": w["witness"]}
                      for (a, b), w in sorted(edges.items())],
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=True)
            f.write("\n")
        return len(edges)

    def check_project(self, ctx):
        edges = self.edges(ctx)
        out = []
        graph = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        for scc in _sccs(graph):
            if len(scc) < 2:
                continue
            cyc = _cycle_in(scc, graph)
            wits = []
            for a, b in zip(cyc, cyc[1:] + cyc[:1]):
                wits.append(f"[{a} -> {b}: {edges[(a, b)]['witness']}]")
            anchor = edges[(cyc[0], cyc[1] if len(cyc) > 1 else cyc[0])]
            out.append(anchor["module"].finding(
                self.rule, anchor["line"],
                "lock-order cycle (potential deadlock): "
                + " ".join(f"{n} ->" for n in cyc) + f" {cyc[0]} — "
                + "; ".join(wits)
                + " — pick ONE order, document it, and take the locks in "
                  "that order everywhere (or split the critical section)"))
        base_path = (os.path.join(ctx.repo_root, LOCK_BASELINE_FILE)
                     if ctx.repo_root else None)
        if base_path and os.path.exists(base_path):
            try:
                with open(base_path, encoding="utf-8") as f:
                    base = {(e["from"], e["to"])
                            for e in json.load(f).get("edges", [])}
            except (OSError, ValueError, KeyError):
                base = None
            if base is None:
                out.append(Finding(
                    self.rule, LOCK_BASELINE_FILE, 1,
                    "unreadable lock-order baseline — regenerate with "
                    "--write-lock-baseline"))
            else:
                for key, w in sorted(edges.items()):
                    if key not in base:
                        out.append(w["module"].finding(
                            self.rule, w["line"],
                            f"new lock-order edge {key[0]} -> {key[1]} is "
                            f"not in the committed {LOCK_BASELINE_FILE} "
                            f"({w['witness']}) — review it for cycles "
                            "against the documented order, then "
                            "regenerate the baseline in this diff"))
                for a, b in sorted(base - set(edges)):
                    out.append(Finding(
                        self.rule, LOCK_BASELINE_FILE, 1,
                        f"stale baseline edge {a} -> {b} is no longer "
                        "observed — regenerate with --write-lock-baseline "
                        "so the documented order stays the real one"))
        return out


# --------------------------------------------------------------------------
# GC07 — use-after-donate
# --------------------------------------------------------------------------

_JIT_NAMES = ("jax.jit", "jit", "pjit", "jax.pjit")


def _donated_indices(fi, idx, expr):
    """Statically-resolvable donated positions from a donate_argnums
    value: int, tuple of ints, a local name bound to one, or a
    conditional between two (union).  None = unresolvable (the pass then
    skips rather than guesses)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return {expr.value}
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = set()
        for e in expr.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.add(e.value)
            else:
                return None
        return out
    if isinstance(expr, ast.IfExp):
        a = _donated_indices(fi, idx, expr.body)
        b = _donated_indices(fi, idx, expr.orelse)
        if a is None and b is None:
            return None
        return (a or set()) | (b or set())
    if isinstance(expr, ast.Name) and fi is not None:
        local = idx.summary(fi).assigns.get(expr.id)
        if local is not None and local is not expr:
            return _donated_indices(fi, idx, local)
    return None


def _find_jit_call(expr):
    """The ``jax.jit(..., donate_argnums=...)`` call inside ``expr``
    (wrapper-transparent: ``wrap_jit(jax.jit(...))`` resolves through),
    or None."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and dotted_chain(n.func) in _JIT_NAMES:
            if any(kw.arg in ("donate_argnums", "donate_argnames")
                   for kw in n.keywords):
                return n
    return None


def _bind_lines(fnnode, chain):
    """Line numbers where ``chain`` (a dotted binding like 'pools' or
    'self._pools') is rebound inside the function."""
    lines = []

    def tgt_chains(t):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from tgt_chains(e)
        elif isinstance(t, ast.Starred):
            yield from tgt_chains(t.value)
        else:
            c = dotted_chain(t)
            if c:
                yield c

    for n in iter_own_nodes(fnnode):
        tgts = []
        if isinstance(n, ast.Assign):
            tgts = n.targets
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign, ast.NamedExpr)):
            tgts = [n.target]
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            tgts = [n.target]
        for t in tgts:
            if chain in tgt_chains(t):
                lines.append(n.lineno)
    return lines


def _loads_of(fnnode, chain):
    """(line, col, node) of every Load of ``chain`` in the function."""
    out = []
    for n in iter_own_nodes(fnnode):
        if "." in chain:
            if (isinstance(n, ast.Attribute)
                    and isinstance(n.ctx, ast.Load)
                    and dotted_chain(n) == chain):
                out.append((n.lineno, n.col_offset, n))
        else:
            if (isinstance(n, ast.Name) and n.id == chain
                    and isinstance(n.ctx, ast.Load)):
                out.append((n.lineno, n.col_offset, n))
    return sorted(out, key=lambda t: (t[0], t[1]))


def _within(node, call):
    end_line = getattr(call, "end_lineno", call.lineno)
    end_col = getattr(call, "end_col_offset", 1 << 30)
    if node.lineno < call.lineno or node.lineno > end_line:
        return False
    if node.lineno == call.lineno and node.col_offset < call.col_offset:
        return False
    if node.lineno == end_line and node.col_offset >= end_col:
        return False
    return True


@register_pass
class UseAfterDonatePass(Pass):
    rule = "GC07"
    summary = ("use-after-donate: a buffer passed at a donate_argnums "
               "position is freed by dispatch — reading the same binding "
               "afterwards (or on the next loop iteration without "
               "rebinding) is a use-after-free")

    def check_project(self, ctx):
        idx = ctx.index
        donating = self._donating_bindings(ctx, idx)
        out = []
        if not donating:
            return out
        by_attr, by_name = {}, {}
        for (rel, kind, name), idxs in donating.items():
            if kind == "attr":
                by_attr.setdefault(name, set()).update(idxs)
            else:
                by_name.setdefault((rel, name), set()).update(idxs)
        for m in ctx.modules:
            for fi in sorted(idx.functions_in(m), key=lambda f: f.qual):
                out.extend(self._check_function(
                    idx, m, fi, by_attr, by_name))
        return out

    def _donating_bindings(self, ctx, idx):
        """{(rel, 'attr'|'name', binding): donated_index_set} plus the
        same through one builder level (a function whose return value is
        a donating jit marks every binding assigned from a call to
        it)."""
        donating = {}
        builder_rets = {}   # FunctionInfo.key -> indices
        for m in ctx.modules:
            for fi in idx.functions_in(m):
                s = idx.summary(fi)
                for expr in s.ret_exprs:
                    jc = _find_jit_call(expr)
                    if jc is not None:
                        idxs = self._indices_of(fi, idx, jc)
                        if idxs:
                            builder_rets[fi.key] = idxs
        for m in ctx.modules:
            for fi in list(idx.functions_in(m)) + [None]:
                body = (fi.node.body if fi is not None else m.tree.body)
                nodes = []
                for stmt in body:
                    if fi is None and isinstance(
                            stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                        continue
                    nodes.extend(
                        n for n in ([stmt] + list(iter_own_nodes(stmt)))
                        if isinstance(n, ast.Assign))
                for n in nodes:
                    if len(n.targets) != 1:
                        continue
                    chain = dotted_chain(n.targets[0])
                    if not chain:
                        continue
                    idxs = None
                    jc = _find_jit_call(n.value)
                    if jc is not None:
                        idxs = self._indices_of(fi, idx, jc)
                    elif isinstance(n.value, ast.Call) and fi is not None:
                        g = idx.resolve_call(m, fi, n.value)
                        if g is not None and g.key in builder_rets:
                            idxs = builder_rets[g.key]
                    if not idxs:
                        continue
                    if chain.startswith("self."):
                        key = (m.rel, "attr", chain.split(".", 1)[1])
                    elif "." not in chain:
                        key = (m.rel, "name", chain)
                    else:
                        continue
                    donating.setdefault(key, set()).update(idxs)
        return donating

    @staticmethod
    def _indices_of(fi, idx, jit_call):
        for kw in jit_call.keywords:
            if kw.arg == "donate_argnums":
                return _donated_indices(fi, idx, kw.value)
        return None   # donate_argnames: positions unresolvable statically

    def _check_function(self, idx, m, fi, by_attr, by_name):
        out = []
        s = idx.summary(fi)
        loops = [n for n in iter_own_nodes(fi.node)
                 if isinstance(n, (ast.For, ast.While, ast.AsyncFor))]
        for call in s.calls:
            f = call.func
            idxs = None
            callee = dotted_chain(f)
            if callee is None:
                continue
            if "." in callee:
                attr = callee.rsplit(".", 1)[1]
                idxs = by_attr.get(attr)
            else:
                idxs = by_name.get((m.rel, callee))
            if not idxs:
                continue
            for i in sorted(idxs):
                if i >= len(call.args):
                    continue
                chain = dotted_chain(call.args[i])
                if not chain or chain == "self":
                    continue
                out.extend(self._check_binding(m, fi, call, chain, loops,
                                               callee, i))
        return out

    def _check_binding(self, m, fi, call, chain, loops, callee, pos):
        out = []
        end_line = getattr(call, "end_lineno", call.lineno)
        binds = _bind_lines(fi.node, chain)
        loads = _loads_of(fi.node, chain)
        # straight-line: reads after the dispatch, before any rebinding
        kill = min((b for b in binds if b >= call.lineno),
                   default=None)
        for line, _col, node in loads:
            if line <= end_line or _within(node, call):
                continue
            if kill is not None and line > kill:
                break
            out.append(m.finding(
                self.rule, node,
                f"use-after-donate: {chain!r} was donated to "
                f"{callee}() (donate_argnums position {pos}, line "
                f"{call.lineno}) — its buffer is freed by dispatch; "
                "rebind the result over it or pass a copy"))
            break   # one finding per donated binding per callsite
        # loop-carried: dispatch inside a loop, binding never rebound in
        # the loop — the next iteration reads a freed buffer
        for loop in loops:
            lend = getattr(loop, "end_lineno", loop.lineno)
            if not (loop.lineno <= call.lineno <= lend):
                continue
            if any(loop.lineno <= b <= lend for b in binds):
                continue
            reads = [n for line, _c, n in loads
                     if loop.lineno <= line <= lend
                     and not _within(n, call)]
            # even with no extra reads, the NEXT iteration's dispatch
            # itself re-reads the freed buffer
            node = reads[0] if reads else call
            out.append(m.finding(
                self.rule, node,
                f"use-after-donate (loop-carried): {chain!r} is "
                f"donated to {callee}() inside this loop but never "
                "rebound — the second iteration dispatches a freed "
                "buffer; rebind the jit's result over it each "
                "iteration"))
            break
        return out


# --------------------------------------------------------------------------
# GC10 — thread lifecycle
# --------------------------------------------------------------------------

_STOPISH = re.compile(
    r"stop|shutdown|clos|running|alive|done|exit|finish|drain|quit|cancel",
    re.IGNORECASE)


@register_pass
class ThreadLifecyclePass(Pass):
    rule = "GC10"
    summary = ("thread lifecycle: every thread must be daemon or provably "
               "joined, and every `while True` loop reachable from a "
               "thread target must read a stop/shutdown flag or return")

    def check_project(self, ctx):
        idx = ctx.index
        out = []
        entries = []
        for m in ctx.modules:
            joins = set()
            starts = []
            for fi in sorted(idx.functions_in(m), key=lambda f: f.qual):
                s = idx.summary(fi)
                joins |= s.joins
                starts.extend((fi, call, bind, line)
                              for call, bind, line in s.threads)
            for fi, call, bind, line in starts:
                target = next(
                    (kw.value for kw in call.keywords
                     if kw.arg == "target"), None)
                daemon = next(
                    (kw.value for kw in call.keywords
                     if kw.arg == "daemon"), None)
                if not (isinstance(daemon, ast.Constant)
                        and daemon.value is True):
                    if bind is None or bind not in joins:
                        out.append(m.finding(
                            self.rule, line,
                            "thread is neither daemon=True nor provably "
                            "joined (no `.join()` on its binding in this "
                            "module) — it outlives shutdown and can hang "
                            "interpreter exit"))
                if target is not None:
                    g = self._resolve_target(idx, m, fi, target)
                    if g is not None:
                        entries.append(g)
        reachable = self._reachable(idx, entries)
        seen_loops = set()
        for fi in sorted(reachable, key=lambda f: (f.module.rel, f.qual)):
            s = idx.summary(fi)
            for loop in s.while_trues:
                key = (fi.module.rel, loop.lineno)
                if key in seen_loops:
                    continue
                seen_loops.add(key)
                if self._loop_can_stop(loop):
                    continue
                out.append(fi.module.finding(
                    self.rule, loop,
                    f"`while True` in thread-reachable {fi.qual!r} never "
                    "reads a stop/shutdown flag and cannot return — the "
                    "thread is unstoppable; check a stop flag (or exit on "
                    "a queue sentinel) each iteration"))
        return out

    @staticmethod
    def _resolve_target(idx, m, fi, target):
        chain = dotted_chain(target)
        if chain is None:
            return None
        parts = chain.split(".")
        if parts[0] in ("self", "cls") and fi.cls is not None:
            return fi.cls.methods.get(parts[-1])
        if len(parts) == 1:
            cur = fi
            while cur is not None:
                hit = cur.nested.get(parts[0])
                if hit is not None:
                    return hit
                cur = cur.parent
            return idx.module_funcs.get(m.rel, {}).get(parts[0])
        mrel = idx.mod_imports.get(m.rel, {}).get(
            "modules", {}).get(parts[0])
        if mrel:
            return idx.module_funcs.get(mrel, {}).get(parts[-1])
        cands = idx.methods_by_name.get(parts[-1], [])
        return cands[0] if len(cands) == 1 else None

    @staticmethod
    def _reachable(idx, entries):
        seen = set()
        work = list(entries)
        reach = []
        while work:
            fi = work.pop()
            if fi.key in seen:
                continue
            seen.add(fi.key)
            reach.append(fi)
            for call in idx.summary(fi).calls:
                g = idx.resolve_call(fi.module, fi, call)
                if g is not None and g.key not in seen:
                    work.append(g)
        return reach

    @staticmethod
    def _loop_can_stop(loop):
        for n in iter_own_nodes(loop):
            if isinstance(n, ast.Return):
                return True
            if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load) \
                    and _STOPISH.search(n.attr):
                return True
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and _STOPISH.search(n.id):
                return True
        return False
