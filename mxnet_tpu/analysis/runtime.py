"""Runtime twins of the static rules — assertions for tests.

``no_retrace()`` is the dynamic half of GC02: the static pass proves a
jitted closure *can't* silently capture mutable state; this context
manager proves a steady-state region *didn't* compile anything.  It
counts XLA backend compilations via ``jax.monitoring`` (every
``jax.jit`` cache miss records ``/jax/core/compile/
backend_compile_duration``) and raises ``RetraceError`` if the count
grew inside the guarded block::

    step(batch)                     # warm-up: traces + compiles
    with no_retrace():
        step(batch)                 # steady state: must be a cache hit

Zero overhead beyond one listener registered on first use; safe to nest.

``tracked()`` is the dynamic half of GC06: the static pass proves the
*visible* nested acquisitions form a DAG, but it cannot see orders that
only materialize at runtime (callbacks, duck-typed callees).  With
``MXNET_LOCKCHECK=1`` (or :func:`arm_lockcheck`), every lock the
threaded modules create through ``tracked(threading.Lock(), "name")``
records, per acquisition, an edge from every lock the acquiring thread
already holds — and raises :class:`LockOrderError`, with both witness
paths, the moment an edge closes a cycle.  Either thread of a would-be
deadlock trips the check on its own, so single-threaded tests catch
inversions that would need a precise two-thread interleaving to actually
deadlock.  Disarmed (the default), ``tracked()`` returns the raw lock —
production pays nothing, not even an isinstance check.
"""

from __future__ import annotations

import contextlib
import threading

__all__ = ["RetraceError", "no_retrace", "compile_count",
           "LockOrderError", "tracked", "arm_lockcheck",
           "lockcheck_armed", "lockcheck_reset", "lockcheck_edges"]


class RetraceError(AssertionError):
    """A region guarded by ``no_retrace()`` triggered XLA compilation."""


_lock = threading.Lock()
_installed = False
_compiles = 0

# every jit/pjit cache miss records exactly one backend compile under
# this key (jax 0.4.x); trace-only events are not counted because a
# pure re-trace that hits the executable cache is not a perf cliff
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _install():
    global _installed
    with _lock:
        if _installed:
            return
        import jax.monitoring as _monitoring

        def _on_duration(key, duration, **kwargs):  # noqa: ARG001
            global _compiles
            if key == _COMPILE_EVENT:
                with _lock:
                    _compiles += 1

        _monitoring.register_event_duration_secs_listener(_on_duration)
        _installed = True


def compile_count():
    """Total XLA backend compiles observed since the listener was
    installed (monotonic; install happens on first call)."""
    _install()
    return _compiles


@contextlib.contextmanager
def no_retrace(allow=0):
    """Assert the wrapped block performs no XLA compilation.

    ``allow`` tolerates that many compiles (e.g. a first-call span that
    legitimately builds one executable).  Raises RetraceError naming the
    overshoot — the runtime analog of a GC02 finding.
    """
    before = compile_count()
    yield
    grew = compile_count() - before
    if grew > allow:
        raise RetraceError(
            f"no_retrace: {grew} XLA compilation(s) inside a steady-state "
            f"region (allowed {allow}) — a jit cache key is unstable "
            "(shape/dtype/static-attr churn) or a closure captured state "
            "that changed; see graftcheck rule GC02")


# --------------------------------------------------------------------------
# GC06 twin — runtime lock-order validation (MXNET_LOCKCHECK=1)
# --------------------------------------------------------------------------

class LockOrderError(AssertionError):
    """A tracked acquisition closed a lock-order cycle (potential
    deadlock): some thread has taken these locks in the opposite
    order."""


_lc_lock = threading.Lock()          # guards the edge graph below
_lc_edges = {}                       # (held, acquired) -> witness str
_lc_armed = None                     # tri-state: None = read the knob
_lc_tls = threading.local()          # .held: [name, ...] per thread


def _knob_armed():
    # routed through config so the knob is typed/defaulted/documented
    # (graftcheck GC03); lazy so the analysis package stays importable
    # standalone (tools/graftcheck.py loads it without mxnet_tpu)
    try:
        from ..config import get_bool
    except ImportError:
        return False
    return get_bool("MXNET_LOCKCHECK")


def lockcheck_armed():
    """Whether ``tracked()`` wraps (MXNET_LOCKCHECK, unless overridden
    by :func:`arm_lockcheck`)."""
    return _lc_armed if _lc_armed is not None else _knob_armed()


def arm_lockcheck(on=True):
    """Force the validator on/off for this process (tests); pass
    ``None`` to defer to the MXNET_LOCKCHECK knob again.  Only locks
    created through ``tracked()`` *while armed* are validated."""
    global _lc_armed
    _lc_armed = on


def lockcheck_reset():
    """Drop every recorded acquisition edge (test isolation)."""
    with _lc_lock:
        _lc_edges.clear()


def lockcheck_edges():
    """Snapshot of the recorded edge set: {(held, acquired): witness}."""
    with _lc_lock:
        return dict(_lc_edges)


def _path(frm, to):
    """Edge list of one path frm -> ... -> to in the recorded graph, or
    None.  Called under _lc_lock."""
    succ = {}
    for a, b in _lc_edges:
        succ.setdefault(a, []).append(b)
    stack, seen = [(frm, [])], {frm}
    while stack:
        node, path = stack.pop()
        for nxt in sorted(succ.get(node, ())):
            edge = (node, nxt)
            if nxt == to:
                return path + [edge]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [edge]))
    return None


class _TrackedLock:
    """Order-recording proxy over a lock.  Delegates acquire/release so
    it also works as the underlying lock of a ``threading.Condition``
    (wait()'s release/re-acquire flows through and stays balanced)."""

    def __init__(self, lock, name):
        self._lock = lock
        self._name = name

    def acquire(self, blocking=True, timeout=-1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._record()
        return got

    def release(self):
        held = getattr(_lc_tls, "held", None)
        if held is not None and self._name in held:
            # remove the most recent entry (locks can unwind out of
            # order under Condition.wait)
            del held[len(held) - 1 - held[::-1].index(self._name)]
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._lock.locked()

    def _record(self):
        held = getattr(_lc_tls, "held", None)
        if held is None:
            held = _lc_tls.held = []
        me = self._name
        with _lc_lock:
            for h in held:
                if h == me:
                    continue   # re-entrant/Condition re-acquire
                edge = (h, me)
                if edge in _lc_edges:
                    continue
                back = _path(me, h)
                if back is not None:
                    wits = "; ".join(
                        f"[{a} -> {b}: {_lc_edges[(a, b)]}]"
                        for a, b in back)
                    raise LockOrderError(
                        f"lock-order cycle: this thread acquired {me!r} "
                        f"while holding {h!r}, but the opposite order "
                        f"{me!r} -> ... -> {h!r} was already recorded: "
                        f"{wits} — two threads taking these corners "
                        "concurrently deadlock; see graftcheck rule GC06")
                _lc_edges[edge] = (
                    f"{threading.current_thread().name} acquired {me} "
                    f"while holding {h}")
        held.append(me)


def tracked(lock, name):
    """Wrap ``lock`` for lock-order validation when the checker is
    armed; return it untouched (zero overhead) otherwise."""
    if lockcheck_armed():
        return _TrackedLock(lock, name)
    return lock
