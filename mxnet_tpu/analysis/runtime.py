"""Runtime twins of the static rules — assertions for tests.

``no_retrace()`` is the dynamic half of GC02: the static pass proves a
jitted closure *can't* silently capture mutable state; this context
manager proves a steady-state region *didn't* compile anything.  It
counts XLA backend compilations via ``jax.monitoring`` (every
``jax.jit`` cache miss records ``/jax/core/compile/
backend_compile_duration``) and raises ``RetraceError`` if the count
grew inside the guarded block::

    step(batch)                     # warm-up: traces + compiles
    with no_retrace():
        step(batch)                 # steady state: must be a cache hit

Zero overhead beyond one listener registered on first use; safe to nest.
"""

from __future__ import annotations

import contextlib
import threading

__all__ = ["RetraceError", "no_retrace", "compile_count"]


class RetraceError(AssertionError):
    """A region guarded by ``no_retrace()`` triggered XLA compilation."""


_lock = threading.Lock()
_installed = False
_compiles = 0

# every jit/pjit cache miss records exactly one backend compile under
# this key (jax 0.4.x); trace-only events are not counted because a
# pure re-trace that hits the executable cache is not a perf cliff
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _install():
    global _installed
    with _lock:
        if _installed:
            return
        import jax.monitoring as _monitoring

        def _on_duration(key, duration, **kwargs):  # noqa: ARG001
            global _compiles
            if key == _COMPILE_EVENT:
                with _lock:
                    _compiles += 1

        _monitoring.register_event_duration_secs_listener(_on_duration)
        _installed = True


def compile_count():
    """Total XLA backend compiles observed since the listener was
    installed (monotonic; install happens on first call)."""
    _install()
    return _compiles


@contextlib.contextmanager
def no_retrace(allow=0):
    """Assert the wrapped block performs no XLA compilation.

    ``allow`` tolerates that many compiles (e.g. a first-call span that
    legitimately builds one executable).  Raises RetraceError naming the
    overshoot — the runtime analog of a GC02 finding.
    """
    before = compile_count()
    yield
    grew = compile_count() - before
    if grew > allow:
        raise RetraceError(
            f"no_retrace: {grew} XLA compilation(s) inside a steady-state "
            f"region (allowed {allow}) — a jit cache key is unstable "
            "(shape/dtype/static-attr churn) or a closure captured state "
            "that changed; see graftcheck rule GC02")
