"""mxnet_tpu.analysis — repo-native static analysis (graftcheck).

CLI: ``python tools/graftcheck.py [paths ...]`` (stdlib-only — runs
before any pip install in CI).  Library surface::

    from mxnet_tpu import analysis
    findings, suppressed, modules = analysis.analyze_paths(["mxnet_tpu"])
    with analysis.runtime.no_retrace():
        step(batch)        # dynamic twin of rule GC02
    self._lock = analysis.tracked(threading.Lock(), "Thing._lock")
                           # dynamic twin of rule GC06 (MXNET_LOCKCHECK=1)

Rules (see the ``passes/`` package and the README "Static analysis"
section): GC01 host-sync on the hot path, GC02 retrace hazards, GC03
env-knob hygiene, GC04 lock discipline, GC05 telemetry-flag discipline,
GC06 lock-order cycles against the committed baseline, GC07
use-after-donate, GC08 atomic-protocol writes, GC09 registry drift,
GC10 thread lifecycle.
Suppress with ``# graftcheck: ignore[GC01] — why it is safe`` (the
justification is mandatory; a bare ignore is itself a finding).
"""

from __future__ import annotations

from . import passes  # noqa: F401 — importing registers GC01–GC10
from . import runtime  # noqa: F401
from .core import (  # noqa: F401
    PASSES, Context, Finding, ModuleInfo, Pass, ProjectIndex, analyze_paths,
    check_source, check_sources, main, register_pass, to_sarif,
)
from .runtime import (  # noqa: F401
    LockOrderError, RetraceError, arm_lockcheck, lockcheck_armed,
    lockcheck_edges, lockcheck_reset, no_retrace, tracked,
)

__all__ = [
    "Finding", "ModuleInfo", "Context", "Pass", "PASSES", "ProjectIndex",
    "register_pass", "analyze_paths", "check_source", "check_sources",
    "main", "to_sarif", "runtime", "no_retrace", "RetraceError",
    "LockOrderError", "tracked", "arm_lockcheck", "lockcheck_armed",
    "lockcheck_edges", "lockcheck_reset",
]
