"""mxnet_tpu.analysis — repo-native static analysis (graftcheck).

CLI: ``python tools/graftcheck.py [paths ...]`` (stdlib-only — runs
before any pip install in CI).  Library surface::

    from mxnet_tpu import analysis
    findings, suppressed, modules = analysis.analyze_paths(["mxnet_tpu"])
    with analysis.runtime.no_retrace():
        step(batch)        # dynamic twin of rule GC02

Rules (see ``passes.py`` and the README "Static analysis" section):
GC01 host-sync on the hot path, GC02 retrace hazards, GC03 env-knob
hygiene, GC04 lock discipline, GC05 telemetry-flag discipline.
Suppress with ``# graftcheck: ignore[GC01] — justification`` (the
justification is mandatory; a bare ignore is itself a finding).
"""

from __future__ import annotations

from . import passes  # noqa: F401 — importing registers GC01–GC05
from . import runtime  # noqa: F401
from .core import (  # noqa: F401
    PASSES, Context, Finding, ModuleInfo, Pass, analyze_paths,
    check_source, main, register_pass,
)
from .runtime import RetraceError, no_retrace  # noqa: F401

__all__ = [
    "Finding", "ModuleInfo", "Context", "Pass", "PASSES", "register_pass",
    "analyze_paths", "check_source", "main", "runtime", "no_retrace",
    "RetraceError",
]
