"""graftcheck core — repo-native static-analysis framework.

The correctness-tooling analog of the reference's cpplint/sanitizer gates
(SURVEY §4): the invariants the runtime PRs rely on — hot-path purity, no
retrace hazards, lock discipline in threaded modules, env-knob hygiene —
are tribal knowledge unless a machine checks them on every push.  This
module is the framework: a pluggable pass registry, per-line suppressions
with mandatory justifications, JSON + SARIF + human output, a
committed-baseline diff mode, and an exit-code contract for CI.  The
passes themselves live in the ``passes/`` package (rules GC01–GC10).

Since PR 19 the framework also carries an **interprocedural layer**
(:class:`ProjectIndex`): a project-wide symbol table (functions, classes,
lock attributes, import aliases, string constants), per-function
summaries (locks acquired, ``with``-held regions and the calls made
inside them, files opened/renamed, threads started, ``while True``
loops, returned path literals) and a call graph with a transitive
may-acquire closure.  The concurrency/protocol passes (GC06–GC10) are
thin rules over these summaries.

Design constraints:

- **stdlib only** — the CI graftcheck lane runs before any pip install,
  so nothing here (or in passes.py) may import jax, numpy, or the
  mxnet_tpu runtime.  Config knowledge (``config.KNOWN_VARS``) is read by
  *parsing* config.py, never importing it.
- **suppressions carry justifications** — ``# graftcheck: ignore[GC01] — why``
  on (or immediately above) the flagged line.  A bare ``ignore[...]``
  with no justification is itself a finding (GC00), so the suppression
  ledger stays reviewable.
- **exit codes**: 0 = clean (no unsuppressed findings), 1 = findings,
  2 = usage/internal error.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import posixpath
import re
import sys
import time

__all__ = [
    "Finding", "ModuleInfo", "Context", "Pass", "PASSES", "register_pass",
    "parse_suppressions", "analyze_paths", "check_source", "check_sources",
    "ProjectIndex", "FunctionInfo", "ClassInfo", "iter_own_nodes",
    "dotted_chain", "call_leaf", "to_sarif", "main",
]

# --------------------------------------------------------------------------
# findings
# --------------------------------------------------------------------------


class Finding:
    """One rule violation at a source location."""

    __slots__ = ("rule", "path", "line", "message", "source_line")

    def __init__(self, rule, path, line, message, source_line=""):
        self.rule = rule
        self.path = path          # repo-relative posix path
        self.line = int(line)
        self.message = message
        self.source_line = source_line

    @property
    def fingerprint(self):
        """Content-addressed identity for baseline diffing: stable across
        unrelated edits that only shift line numbers."""
        text = self.source_line.strip() or f"line{self.line}"
        h = hashlib.sha1(
            f"{self.rule}|{self.path}|{text}".encode()).hexdigest()
        return h[:16]

    def to_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "fingerprint": self.fingerprint}

    def render(self):
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def __repr__(self):
        return f"<Finding {self.render()}>"


# --------------------------------------------------------------------------
# suppressions
# --------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*graftcheck:\s*ignore\[([A-Za-z0-9_,\s]+)\]\s*(?:[-—–:]+\s*(\S.*))?")
_COMMENT_ONLY_RE = re.compile(r"^\s*(#|$)")


def parse_suppressions(lines):
    """Map line number (1-based) -> (rules, justification, comment_line).

    A suppression on a code line applies to that line; on a comment-only
    line it applies to the next code line (stacked comment lines chain).
    A trailing suppression with no code line to govern is kept under the
    line past EOF so the hygiene checks still see it.
    """
    out = {}
    pending = []  # suppressions waiting for the next code line
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        entry = None
        if m:
            rules = frozenset(
                r.strip().upper() for r in m.group(1).split(",") if r.strip())
            entry = (rules, (m.group(2) or "").strip(), i)
        if _COMMENT_ONLY_RE.match(text):
            if entry:
                pending.append(entry)
            continue
        # a code line: attach its own inline suppression plus any pending
        here = list(pending)
        pending = []
        if entry:
            here.append(entry)
        if here:
            rules = frozenset().union(*(e[0] for e in here))
            just = "; ".join(e[1] for e in here if e[1])
            out[i] = (rules, just, here[0][2])
    if pending:
        # dangling at EOF: governs nothing, but must not vanish silently
        rules = frozenset().union(*(e[0] for e in pending))
        just = "; ".join(e[1] for e in pending if e[1])
        out[len(lines) + 1] = (rules, just, pending[0][2])
    return out


# --------------------------------------------------------------------------
# module / project context
# --------------------------------------------------------------------------


class ModuleInfo:
    """One parsed source file plus its suppression map."""

    def __init__(self, path, rel, text):
        self.path = path          # display path (repo-relative when known)
        self.rel = rel            # path relative to the package root, posix
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.suppressions = parse_suppressions(self.lines)

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule, node_or_line, message):
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(rule, self.path, line, message, self.line_text(line))


class Context:
    """Project-wide state shared by passes: every module, plus the repo /
    package roots so cross-file rules (knob catalog vs README) can see
    both sides."""

    def __init__(self, modules, package_root=None, repo_root=None):
        self.modules = modules
        self.package_root = package_root
        self.repo_root = repo_root
        self._index = None

    @property
    def index(self):
        """Lazy project-wide :class:`ProjectIndex` (built on first use so
        module-local passes pay nothing for it)."""
        if self._index is None:
            self._index = ProjectIndex(self)
        return self._index

    def module(self, rel):
        for m in self.modules:
            if m.rel == rel:
                return m
        return None

    def read_repo_file(self, name):
        if not self.repo_root:
            return None
        p = os.path.join(self.repo_root, name)
        try:
            with open(p, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None


# --------------------------------------------------------------------------
# interprocedural layer: symbol index + per-function summaries + call graph
# --------------------------------------------------------------------------

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


def dotted_chain(node):
    """``'a.b.c'`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_leaf(call):
    """Leaf name of a call's func (``'replace'`` for ``os.replace(...)``)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def iter_own_nodes(node):
    """Every AST node lexically inside ``node``'s body, NOT descending
    into nested function/class/lambda definitions (their bodies run at a
    different time, under different locks)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def _lockish(name):
    low = name.lower()
    return "lock" in low or "mutex" in low


def _lock_ctor_of(expr):
    """``'Lock'``/``'RLock'``/... when ``expr`` constructs a threading
    primitive (``threading.Lock()``, bare ``Lock()``), else None.
    Wrapper-transparent: ``tracked(threading.Lock(), "name")`` — the
    MXNET_LOCKCHECK runtime validator — is still a Lock."""
    if not isinstance(expr, ast.Call):
        return None
    leaf = call_leaf(expr)
    if leaf in _LOCK_CTORS:
        return leaf
    for arg in expr.args:
        inner = _lock_ctor_of(arg)
        if inner is not None:
            return inner
    return None


class FunctionInfo:
    """One function/method (nested defs included) in the project index."""

    __slots__ = ("module", "qual", "cls", "name", "node", "parent", "nested")

    def __init__(self, module, qual, cls, name, node, parent=None):
        self.module = module      # ModuleInfo
        self.qual = qual          # 'Router._dispatch_loop', 'f.<locals>.g'
        self.cls = cls            # owning ClassInfo or None
        self.name = name
        self.node = node
        self.parent = parent      # enclosing FunctionInfo for nested defs
        self.nested = {}          # name -> FunctionInfo

    @property
    def key(self):
        return (self.module.rel, self.qual)

    def __repr__(self):
        return f"<FunctionInfo {self.module.rel}::{self.qual}>"


class ClassInfo:
    """One class: its methods plus the lock attributes its methods assign
    (``self.X = threading.Lock()``) and Condition->lock aliases
    (``self.C = threading.Condition(self.X)`` acquires X's lock)."""

    __slots__ = ("module", "name", "node", "methods", "lock_attrs",
                 "lock_aliases")

    def __init__(self, module, node):
        self.module = module
        self.name = node.name
        self.node = node
        self.methods = {}         # name -> FunctionInfo
        self.lock_attrs = {}      # attr -> ctor name ('Lock', 'RLock', ...)
        self.lock_aliases = {}    # attr -> underlying lock attr


class FnSummary:
    """Per-function facts the concurrency/protocol passes consume.

    ``acquires``      [(lock_id, line)] — every ``with <lock>:`` entered.
    ``pairs``         [(held_id, inner_id, held_line, inner_line)] — a
                      lock acquired while another is lexically held.
    ``region_calls``  [(held_id, held_line, Call)] — calls made while a
                      lock is held (the interprocedural edge source).
    ``calls``         [Call] — every call in the body.
    ``opens``         [(mode, Call, line)] — every builtin ``open``.
    ``replaces``      [(Call, line)] — ``os.replace`` / ``os.rename``.
    ``ret_exprs``     [expr] — returned expressions (path-literal carrier).
    ``threads``       [(Call, bind_chain, line)] — threading.Thread(...).
    ``joins``         {dotted chain} — receivers of ``.join()`` calls.
    ``while_trues``   [While] — literal ``while True:`` loops.
    ``assigns``       {name: expr} — first simple local assignment.
    """

    __slots__ = ("acquires", "pairs", "region_calls", "calls", "opens",
                 "replaces", "ret_exprs", "threads", "joins",
                 "while_trues", "assigns")

    def __init__(self):
        self.acquires = []
        self.pairs = []
        self.region_calls = []
        self.calls = []
        self.opens = []
        self.replaces = []
        self.ret_exprs = []
        self.threads = []
        self.joins = set()
        self.while_trues = []
        self.assigns = {}


class ProjectIndex:
    """Project-wide symbol table + summaries + call graph.

    Built once per Context (lazily via ``ctx.index``), stdlib-only, no
    imports of analyzed code.  Resolution is deliberately conservative:
    an unresolvable call or lock receiver yields *no* edge rather than a
    guessed one, so passes built on top under-approximate instead of
    spraying false positives.
    """

    def __init__(self, ctx):
        self.ctx = ctx
        self.rels = {m.rel for m in ctx.modules}
        self.functions = {}          # (rel, qual) -> FunctionInfo
        self.classes = {}            # (rel, name) -> ClassInfo
        self.module_funcs = {}       # rel -> {name: FunctionInfo}
        self.methods_by_name = {}    # method name -> [FunctionInfo]
        self.classes_by_lock_attr = {}   # attr -> [ClassInfo]
        self.module_lock_globals = {}    # rel -> {name: ctor}
        self.module_consts = {}      # rel -> {NAME: str}
        self.mod_imports = {}        # rel -> {'modules': {...}, 'symbols': {...}}
        self._summaries = {}
        self._may_acquire = {}
        self._ret_tokens = {}
        for m in ctx.modules:
            self._index_module(m)

    # -- construction -------------------------------------------------------

    def _module_file(self, prefix):
        """Map a package-relative dotted/posix prefix to a known module
        rel (``serving/replica`` -> ``serving/replica.py``)."""
        if prefix is None:
            return None
        for cand in ((prefix + ".py") if prefix else "__init__.py",
                     posixpath.join(prefix, "__init__.py") if prefix
                     else "__init__.py"):
            if cand in self.rels:
                return cand
        return None

    def _resolve_from(self, rel, module, level):
        """Package-relative dir prefix an ImportFrom targets, or None when
        it escapes the package / is third-party."""
        if level == 0:
            if module and (module == "mxnet_tpu"
                           or module.startswith("mxnet_tpu.")):
                return module[len("mxnet_tpu"):].lstrip(".").replace(".", "/")
            return None
        base = posixpath.dirname(rel)
        for _ in range(level - 1):
            if not base:
                return None    # relative import escapes the package
            base = posixpath.dirname(base)
        sub = (module or "").replace(".", "/")
        return posixpath.join(base, sub) if sub else base

    def _index_module(self, m):
        rel = m.rel
        self.module_funcs[rel] = {}
        self.module_lock_globals[rel] = {}
        consts = self.module_consts[rel] = {}
        imports = self.mod_imports[rel] = {"modules": {}, "symbols": {}}
        for node in ast.walk(m.tree):
            if isinstance(node, ast.ImportFrom):
                base = self._resolve_from(rel, node.module, node.level)
                if base is None:
                    continue
                for al in node.names:
                    asname = al.asname or al.name
                    sub = (posixpath.join(base, al.name) if base
                           else al.name)
                    mrel = self._module_file(sub)
                    if mrel:
                        imports["modules"][asname] = mrel
                    else:
                        owner = self._module_file(base)
                        if owner:
                            imports["symbols"][asname] = (owner, al.name)
            elif isinstance(node, ast.Import):
                for al in node.names:
                    if al.asname and al.name.startswith("mxnet_tpu"):
                        sub = al.name[len("mxnet_tpu"):].lstrip(".")
                        mrel = self._module_file(sub.replace(".", "/"))
                        if mrel:
                            imports["modules"][al.asname] = mrel
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(m, node, None, None, node.name)
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(m, node)
                self.classes[(rel, ci.name)] = ci
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        fi = self._add_function(
                            m, sub, ci, None, f"{ci.name}.{sub.name}")
                        ci.methods[sub.name] = fi
                self._scan_lock_attrs(ci)
            elif (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                tname = node.targets[0].id
                if (isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    consts[tname] = node.value.value
                else:
                    ctor = _lock_ctor_of(node.value)
                    if ctor:
                        self.module_lock_globals[rel][tname] = ctor

    def _add_function(self, m, node, cls, parent, qual):
        fi = FunctionInfo(m, qual, cls, node.name, node, parent)
        self.functions[fi.key] = fi
        if cls is None and parent is None:
            self.module_funcs[m.rel].setdefault(node.name, fi)
        if cls is not None and parent is None:
            self.methods_by_name.setdefault(node.name, []).append(fi)
        for sub in iter_own_nodes(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child = self._add_function(
                    m, sub, cls, fi, f"{qual}.<locals>.{sub.name}")
                fi.nested[sub.name] = child
        return fi

    def _scan_lock_attrs(self, ci):
        for meth in ci.methods.values():
            for node in iter_own_nodes(meth.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                tgt = node.targets[0]
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                ctor = _lock_ctor_of(node.value)
                if ctor is None:
                    continue
                if ctor == "Condition" and node.value.args:
                    base = dotted_chain(node.value.args[0])
                    if base and base.startswith("self."):
                        ci.lock_aliases[tgt.attr] = base.split(".", 1)[1]
                        continue
                ci.lock_attrs[tgt.attr] = ctor
                self.classes_by_lock_attr.setdefault(tgt.attr, []).append(ci)

    # -- lock identity --------------------------------------------------------

    def lock_id(self, fi, expr):
        """Canonical identity of a lock acquisition expression, or None
        when ``expr`` is not recognisably a lock.

        Identities are ``rel::Class.attr`` for instance locks (Condition
        aliases resolved to the underlying lock), ``rel::name`` for
        module globals, ``rel::*.attr`` for lockish attrs on receivers no
        class claims.  Scoping by class keeps two ``_lock``\\ s in one
        module distinct; matching by attribute NAME (not instance) is the
        standard lock-*class* abstraction for order analysis.
        """
        chain = dotted_chain(expr)
        if chain is None:
            return None
        parts = chain.split(".")
        leaf = parts[-1]
        rel = fi.module.rel if fi is not None else None
        cls = fi.cls if fi is not None else None
        if len(parts) >= 2 and parts[0] in ("self", "cls") and cls:
            attr = cls.lock_aliases.get(leaf, leaf)
            if attr in cls.lock_attrs or _lockish(attr):
                return f"{rel}::{cls.name}.{attr}"
            return None
        if len(parts) == 1:
            if leaf in self.module_lock_globals.get(rel, ()):
                return f"{rel}::{leaf}"
            return f"{rel}::{leaf}" if _lockish(leaf) else None
        cands = self.classes_by_lock_attr.get(leaf, [])
        same = [c for c in cands if c.module.rel == rel]
        pick = (same[0] if len(same) == 1
                else cands[0] if len(cands) == 1 else None)
        if pick is not None:
            attr = pick.lock_aliases.get(leaf, leaf)
            return f"{pick.module.rel}::{pick.name}.{attr}"
        return f"{rel}::*.{leaf}" if _lockish(leaf) else None

    def lock_ctor(self, lock_id):
        """Constructor name behind an identity ('Lock', 'RLock', ...) or
        None when unknown."""
        rel, _, tail = lock_id.partition("::")
        if "." in tail:
            clsname, attr = tail.split(".", 1)
            ci = self.classes.get((rel, clsname))
            if ci:
                return ci.lock_attrs.get(attr)
            return None
        return self.module_lock_globals.get(rel, {}).get(tail)

    # -- summaries ------------------------------------------------------------

    def summary(self, fi):
        s = self._summaries.get(fi.key)
        if s is not None:
            return s
        s = FnSummary()
        self._summaries[fi.key] = s
        held = []           # [(lock_id, line)] lexically-held stack
        thread_binds = {}   # id(call) -> bound chain

        def walk(n):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                return
            if isinstance(n, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in n.items:
                    walk(item.context_expr)
                    lid = self.lock_id(fi, item.context_expr)
                    if lid is not None:
                        ln = item.context_expr.lineno
                        s.acquires.append((lid, ln))
                        for h, hl in held:
                            if h != lid:
                                s.pairs.append((h, lid, hl, ln))
                        held.append((lid, ln))
                        pushed += 1
                for b in n.body:
                    walk(b)
                if pushed:
                    del held[-pushed:]
                return
            if isinstance(n, ast.Assign) and len(n.targets) == 1:
                tgt = n.targets[0]
                if isinstance(tgt, ast.Name):
                    s.assigns.setdefault(tgt.id, n.value)
                if isinstance(n.value, ast.Call):
                    chain = dotted_chain(tgt)
                    if chain and call_leaf(n.value) == "Thread":
                        thread_binds[id(n.value)] = chain
            elif isinstance(n, ast.Call):
                s.calls.append(n)
                for h, hl in held:
                    s.region_calls.append((h, hl, n))
                leaf = call_leaf(n)
                fchain = dotted_chain(n.func)
                if leaf == "open" and fchain == "open" and n.args:
                    mode = "r"
                    if len(n.args) >= 2 and isinstance(n.args[1],
                                                       ast.Constant):
                        mode = str(n.args[1].value)
                    for kw in n.keywords:
                        if kw.arg == "mode" and isinstance(kw.value,
                                                           ast.Constant):
                            mode = str(kw.value.value)
                    s.opens.append((mode, n, n.lineno))
                elif leaf in ("replace", "rename") and fchain in (
                        "os.replace", "os.rename"):
                    s.replaces.append((n, n.lineno))
                elif leaf == "Thread" and fchain in ("threading.Thread",
                                                     "Thread"):
                    s.threads.append((n, thread_binds.get(id(n)), n.lineno))
                elif leaf == "join" and isinstance(n.func, ast.Attribute):
                    chain = dotted_chain(n.func.value)
                    if chain:
                        s.joins.add(chain)
            elif isinstance(n, ast.While):
                if (isinstance(n.test, ast.Constant)
                        and n.test.value is True):
                    s.while_trues.append(n)
            elif isinstance(n, ast.Return) and n.value is not None:
                s.ret_exprs.append(n.value)
            for c in ast.iter_child_nodes(n):
                walk(c)

        for stmt in fi.node.body:
            walk(stmt)
        return s

    # -- call resolution --------------------------------------------------------

    def resolve_call(self, module, fi, call):
        """FunctionInfo a call dispatches to, or None.  Conservative:
        self-methods, module functions, nested defs, imported project
        symbols, ``alias.func`` through a project-module alias, and
        method names defined by exactly one class (module-local first,
        then project-wide)."""
        f = call.func
        if isinstance(f, ast.Name):
            cur = fi
            while cur is not None:
                hit = cur.nested.get(f.id)
                if hit is not None:
                    return hit
                cur = cur.parent
            hit = self.module_funcs.get(module.rel, {}).get(f.id)
            if hit is not None:
                return hit
            sym = self.mod_imports.get(module.rel, {}).get(
                "symbols", {}).get(f.id)
            if sym:
                owner, name = sym
                return self.module_funcs.get(owner, {}).get(name)
            return None
        if not isinstance(f, ast.Attribute):
            return None
        leaf = f.attr
        recv = f.value
        if isinstance(recv, ast.Name):
            if recv.id in ("self", "cls") and fi is not None and fi.cls:
                hit = fi.cls.methods.get(leaf)
                if hit is not None:
                    return hit
            mrel = self.mod_imports.get(module.rel, {}).get(
                "modules", {}).get(recv.id)
            if mrel:
                return self.module_funcs.get(mrel, {}).get(leaf)
        cands = self.methods_by_name.get(leaf, [])
        same = [c for c in cands if c.module.rel == module.rel]
        if len(same) == 1:
            return same[0]
        if len(cands) == 1:
            return cands[0]
        return None

    # -- transitive lock closure --------------------------------------------------

    def may_acquire(self, fi, _stack=None):
        """{lock_id: (call_chain, site)} — every lock ``fi`` may take,
        directly or through resolvable calls.  ``call_chain`` is the
        tuple of ``rel::qual`` hops from ``fi`` to the acquiring
        function (empty = direct), ``site`` the acquisition ``rel:line``.
        Recursion through call-graph cycles is cut (first visit wins)."""
        if fi.key in self._may_acquire:
            return self._may_acquire[fi.key]
        if _stack is None:
            _stack = set()
        if fi.key in _stack:
            return {}
        _stack.add(fi.key)
        out = {}
        s = self.summary(fi)
        for lid, ln in s.acquires:
            out.setdefault(lid, ((), f"{fi.module.rel}:{ln}"))
        for call in s.calls:
            g = self.resolve_call(fi.module, fi, call)
            if g is None:
                continue
            for lid, (chain, site) in self.may_acquire(g, _stack).items():
                out.setdefault(
                    lid, ((f"{g.module.rel}::{g.qual}",) + chain, site))
        _stack.discard(fi.key)
        self._may_acquire[fi.key] = out
        return out

    # -- string/path token resolution ----------------------------------------------

    def expr_tokens(self, fi, expr, _depth=0, _seen=None):
        """Every string literal an expression can carry: constants,
        f-string fragments, module-level string constants, simple local
        assignments, and (one call deep per level, 3 levels max) the
        returned literals of resolvable project helpers — so
        ``open(self._state_path() + '.tmp')`` resolves through
        ``_state_path`` to ``{'router.json', '.tmp'}``."""
        if _seen is None:
            _seen = set()
        toks = set()
        if _depth > 3:
            return toks
        for n in ast.walk(expr):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                if n.value:
                    toks.add(n.value)
            elif isinstance(n, ast.Name):
                key = (fi.key if fi else None, n.id)
                if key in _seen:
                    continue
                _seen.add(key)
                rel = fi.module.rel if fi else None
                const = self.module_consts.get(rel, {}).get(n.id)
                if const:
                    toks.add(const)
                elif fi is not None:
                    local = self.summary(fi).assigns.get(n.id)
                    if local is not None and local is not expr:
                        toks |= self.expr_tokens(fi, local, _depth + 1,
                                                 _seen)
            elif isinstance(n, ast.Call):
                g = self.resolve_call(fi.module, fi, n) if fi else None
                if g is not None:
                    toks |= self.ret_tokens(g, _depth + 1)
        return toks

    def ret_tokens(self, fi, _depth=0):
        """String literals a function's return expressions can carry."""
        if fi.key in self._ret_tokens:
            return self._ret_tokens[fi.key]
        self._ret_tokens[fi.key] = set()   # cycle cut
        toks = set()
        if _depth <= 3:
            for expr in self.summary(fi).ret_exprs:
                toks |= self.expr_tokens(fi, expr, _depth)
        self._ret_tokens[fi.key] = toks
        return toks

    # -- convenience ----------------------------------------------------------------

    def functions_in(self, module):
        return [fi for fi in self.functions.values()
                if fi.module is module]


# --------------------------------------------------------------------------
# pass registry
# --------------------------------------------------------------------------


class Pass:
    """Base class for one rule.  Subclasses set ``rule`` + ``summary`` and
    implement ``check_module`` (per file) and/or ``check_project``
    (cross-file, runs once with the full Context)."""

    rule = "GC00"
    summary = ""

    def check_module(self, module, ctx):  # noqa: ARG002
        return []

    def check_project(self, ctx):  # noqa: ARG002
        return []


PASSES: list = []


def register_pass(cls):
    """Decorator adding a Pass subclass to the registry (pluggable: any
    module imported before the run may register more)."""
    PASSES.append(cls())
    return cls


# --------------------------------------------------------------------------
# runner
# --------------------------------------------------------------------------


def _iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git", ".claude"))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def _package_rel(path):
    """Path of a module relative to its enclosing ``mxnet_tpu`` package
    (what HOT_PATHS / THREADED_MODULES key on); falls back to basename."""
    parts = os.path.abspath(path).replace(os.sep, "/").split("/")
    if "mxnet_tpu" in parts:
        idx = len(parts) - 1 - parts[::-1].index("mxnet_tpu")
        return "/".join(parts[idx + 1:])
    return parts[-1]


def load_module(path, repo_root=None):
    with open(path, encoding="utf-8") as f:
        text = f.read()
    display = path
    if repo_root:
        try:
            display = os.path.relpath(path, repo_root).replace(os.sep, "/")
        except ValueError:
            pass
    return ModuleInfo(display, _package_rel(path), text)


def _apply_suppressions(module, findings):
    """Split raw findings into (kept, suppressed) per the module's
    suppression map.  An unjustified suppression never suppresses (its
    GC00 comes from _check_suppression_rules, which sees every ignore
    whether or not a finding matched)."""
    kept, suppressed = [], []
    for f in findings:
        sup = module.suppressions.get(f.line)
        if sup and f.rule in sup[0] and sup[1]:
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed


def _check_suppression_rules(module, known_rules):
    """Hygiene over EVERY ignore[...] comment, matched or not: unknown
    rule ids are typos that disable nothing, and a missing justification
    is itself a finding — both keep the suppression ledger reviewable."""
    out = []
    seen = set()
    for line, (rules, just, at) in sorted(module.suppressions.items()):
        if at in seen:
            continue
        seen.add(at)
        for r in sorted(rules):
            if r not in known_rules:
                out.append(module.finding(
                    "GC00", at, f"unknown rule {r!r} in suppression "
                    f"(known: {', '.join(sorted(known_rules))})"))
        if not just:
            out.append(module.finding(
                "GC00", at,
                "suppression has no justification — write "
                f"'# graftcheck: ignore[{', '.join(sorted(rules))}] — "
                "why this is safe'"))
    return out


def build_context(paths, repo_root=None):
    """Load every .py under ``paths`` into a Context.  Returns
    (ctx, errors) where errors are GC00 syntax-error findings."""
    modules, errors = [], []
    for path in _iter_py_files(paths):
        try:
            modules.append(load_module(path, repo_root=repo_root))
        except SyntaxError as e:
            errors.append(Finding("GC00", path, e.lineno or 0,
                                  f"syntax error: {e.msg}"))
    package_root = None
    for m in modules:
        if m.rel == "config.py":
            package_root = os.path.dirname(os.path.abspath(
                os.path.join(repo_root or ".", m.path)))
    ctx = Context(modules, package_root=package_root, repo_root=repo_root)
    return ctx, errors


def _selected_passes(select=None, ignore=None):
    passes = list(PASSES)
    if select is not None:
        want = {r.upper() for r in select}
        passes = [p for p in passes if p.rule in want]
    if ignore:
        skip = {r.upper() for r in ignore}
        passes = [p for p in passes if p.rule not in skip]
    return passes


def analyze_context(ctx, errors=(), select=None, ignore=None, stats=None):
    """Run the (selected) registered passes over a prebuilt Context.

    Returns (findings, suppressed, modules); ``stats`` (optional dict) is
    filled with ``rule -> {'seconds': s, 'findings': n}``.
    """
    modules = ctx.modules
    passes = _selected_passes(select, ignore)
    known_rules = {p.rule for p in PASSES} | {"GC00"}
    all_kept, all_suppressed = list(errors), []
    by_module = {id(m): [] for m in modules}
    for p in passes:
        t0 = time.perf_counter()
        raw = []
        for m in modules:
            raw.extend(p.check_module(m, ctx))
        raw.extend(p.check_project(ctx))
        if stats is not None:
            stats[p.rule] = {"seconds": time.perf_counter() - t0,
                             "findings": len(raw)}
        for f in raw:
            m = next((mm for mm in modules if mm.path == f.path), None)
            if m is not None:
                by_module[id(m)].append(f)
            else:
                all_kept.append(f)
    hygiene = select is None or "GC00" in {r.upper() for r in select}
    if ignore and "GC00" in {r.upper() for r in ignore}:
        hygiene = False
    for m in modules:
        kept, suppressed = _apply_suppressions(m, by_module[id(m)])
        if hygiene:
            kept.extend(_check_suppression_rules(m, known_rules))
        all_kept.extend(kept)
        all_suppressed.extend(suppressed)
    all_kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return all_kept, all_suppressed, modules


def analyze_paths(paths, repo_root=None, select=None, ignore=None,
                  stats=None):
    """Run every registered pass over ``paths``.

    Returns (findings, suppressed, modules) — findings are unsuppressed.
    """
    ctx, errors = build_context(paths, repo_root=repo_root)
    return analyze_context(ctx, errors, select=select, ignore=ignore,
                           stats=stats)


def check_source(source, rel="module.py", path=None):
    """Test helper: run all passes over one in-memory source snippet as if
    it lived at ``rel`` inside the mxnet_tpu package.  Returns
    (findings, suppressed)."""
    return check_sources({rel: source}, path=path)


def check_sources(sources, path=None, repo_root=None):
    """Test helper: run all passes over several in-memory modules at once
    (``{rel: source}``) so cross-file rules (lock order through calls,
    chaos-registry drift) are exercisable without touching disk.  Returns
    (findings, suppressed) over all modules."""
    modules = [ModuleInfo(path or rel, rel, src)
               for rel, src in sorted(sources.items())]
    ctx = Context(modules, repo_root=repo_root)
    findings, suppressed, _ = analyze_context(ctx)
    return findings, suppressed


# --------------------------------------------------------------------------
# baseline
# --------------------------------------------------------------------------


def load_baseline(path):
    """Baseline as a MULTISET {(rule, path, fingerprint): count} —
    identical-text findings share a fingerprint, so each baseline entry
    must excuse exactly one occurrence or a copy-pasted new violation
    would hide behind an old one."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    counts: dict = {}
    for e in data.get("findings", []):
        k = (e["rule"], e["path"], e["fingerprint"])
        counts[k] = counts.get(k, 0) + 1
    return counts


def write_baseline(path, findings):
    data = {
        "comment": "graftcheck baseline — known findings new code is "
                   "diffed against; regenerate with --write-baseline",
        "findings": [
            {"rule": f.rule, "path": f.path, "fingerprint": f.fingerprint,
             "message": f.message}
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


# --------------------------------------------------------------------------
# SARIF (GitHub code-scanning annotations)
# --------------------------------------------------------------------------


def to_sarif(findings, passes=None):
    """Findings as a SARIF 2.1.0 document (one run, one result per
    finding, fingerprints carried for alert dedup)."""
    rules = [{"id": p.rule,
              "shortDescription": {"text": p.summary or p.rule}}
             for p in (passes if passes is not None else PASSES)]
    if not any(r["id"] == "GC00" for r in rules):
        rules.insert(0, {"id": "GC00", "shortDescription": {
            "text": "suppression hygiene / parse errors"}})
    return {
        "version": "2.1.0",
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "runs": [{
            "tool": {"driver": {
                "name": "graftcheck",
                "informationUri":
                    "https://github.com/apache/incubator-mxnet",
                "rules": rules,
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": f.message},
                "partialFingerprints": {"graftcheck/v1": f.fingerprint},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, f.line)},
                }}],
            } for f in findings],
        }],
    }


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

_USAGE = """\
usage: graftcheck.py [paths ...] [options]

Repo-native static analysis: hot-path purity (GC01), retrace hazards
(GC02), env-knob hygiene (GC03), lock discipline (GC04), telemetry-flag
discipline (GC05), lock-order cycles (GC06), use-after-donate (GC07),
atomic-protocol writes (GC08), registry drift (GC09), thread lifecycle
(GC10).  Default path: the mxnet_tpu package next to tools/.

options:
  --json                 machine-readable findings on stdout
  --sarif FILE           also write findings as SARIF 2.1.0 ('-' = stdout)
  --list-rules           print the rule table and exit
  --select RULES         run only these comma-separated rules
  --ignore RULES         skip these comma-separated rules
  --stats                per-rule timing/findings table on stderr
  --baseline FILE        ignore findings recorded in FILE (diff mode)
  --write-baseline FILE  write current findings to FILE and exit 0
  --write-lock-baseline FILE
                         write the GC06 lock-order edge set to FILE
                         (the committed graftcheck-lockorder.json)
  -q, --quiet            suppress the summary line
"""


def main(argv=None, repo_root=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = quiet = want_stats = False
    baseline_path = write_baseline_path = None
    sarif_path = lock_baseline_path = None
    select = ignore = None
    paths = []
    i = 0

    def _arg(flag):
        nonlocal i
        i += 1
        if i >= len(argv):
            print(f"{flag} needs an argument", file=sys.stderr)
            return None
        return argv[i]

    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print(_USAGE)
            return 0
        if a == "--json":
            as_json = True
        elif a in ("-q", "--quiet"):
            quiet = True
        elif a == "--stats":
            want_stats = True
        elif a == "--list-rules":
            for p in PASSES:
                print(f"{p.rule}  {p.summary}")
            return 0
        elif a == "--baseline":
            baseline_path = _arg(a)
            if baseline_path is None:
                return 2
        elif a == "--write-baseline":
            write_baseline_path = _arg(a)
            if write_baseline_path is None:
                return 2
        elif a == "--write-lock-baseline":
            lock_baseline_path = _arg(a)
            if lock_baseline_path is None:
                return 2
        elif a == "--sarif":
            sarif_path = _arg(a)
            if sarif_path is None:
                return 2
        elif a == "--select":
            v = _arg(a)
            if v is None:
                return 2
            select = [r.strip() for r in v.split(",") if r.strip()]
        elif a == "--ignore":
            v = _arg(a)
            if v is None:
                return 2
            ignore = [r.strip() for r in v.split(",") if r.strip()]
        elif a.startswith("-"):
            print(f"unknown option {a!r}\n{_USAGE}", file=sys.stderr)
            return 2
        else:
            paths.append(a)
        i += 1

    if repo_root is None:
        repo_root = os.getcwd()
    if not paths:
        default = os.path.join(repo_root, "mxnet_tpu")
        if not os.path.isdir(default):
            print("no paths given and no ./mxnet_tpu found", file=sys.stderr)
            return 2
        paths = [default]
    for p in paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    stats = {} if want_stats else None
    try:
        ctx, errors = build_context(paths, repo_root=repo_root)
        if lock_baseline_path:
            gc06 = next((p for p in PASSES if p.rule == "GC06"), None)
            if gc06 is None or not hasattr(gc06, "write_lock_baseline"):
                print("GC06 lock-order pass is not registered",
                      file=sys.stderr)
                return 2
            n = gc06.write_lock_baseline(lock_baseline_path, ctx)
            if not quiet:
                print(f"wrote {n} lock-order edge(s) to "
                      f"{lock_baseline_path}")
            return 0
        findings, suppressed, modules = analyze_context(
            ctx, errors, select=select, ignore=ignore, stats=stats)
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"graftcheck internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if want_stats:
        total = sum(s["seconds"] for s in stats.values())
        print(f"{'rule':<6} {'seconds':>8} {'findings':>9}",
              file=sys.stderr)
        for rule in sorted(stats):
            s = stats[rule]
            print(f"{rule:<6} {s['seconds']:>8.3f} {s['findings']:>9}",
                  file=sys.stderr)
        print(f"{'total':<6} {total:>8.3f}", file=sys.stderr)

    if write_baseline_path:
        write_baseline(write_baseline_path, findings)
        if not quiet:
            print(f"wrote {len(findings)} finding(s) to "
                  f"{write_baseline_path}")
        return 0

    if baseline_path:
        try:
            base = load_baseline(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"cannot read baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        remaining, kept = dict(base), []
        for f in findings:
            k = (f.rule, f.path, f.fingerprint)
            if remaining.get(k):
                remaining[k] -= 1  # each entry excuses ONE occurrence
            else:
                kept.append(f)
        findings = kept

    if sarif_path:
        passes = _selected_passes(select, ignore)
        doc = to_sarif(findings, passes)
        if sarif_path == "-":
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            with open(sarif_path, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=2, sort_keys=True)
                f.write("\n")

    if as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "suppressed": len(suppressed),
            "files": len(modules),
        }, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        if not quiet:
            print(f"graftcheck: {len(findings)} finding(s), "
                  f"{len(suppressed)} suppressed, {len(modules)} file(s)"
                  + (" [vs baseline]" if baseline_path else ""))
    return 1 if findings else 0
