"""graftcheck core — repo-native static-analysis framework.

The correctness-tooling analog of the reference's cpplint/sanitizer gates
(SURVEY §4): the invariants the runtime PRs rely on — hot-path purity, no
retrace hazards, lock discipline in threaded modules, env-knob hygiene —
are tribal knowledge unless a machine checks them on every push.  This
module is the framework: a pluggable pass registry, per-line suppressions
with mandatory justifications, JSON + human output, a committed-baseline
diff mode, and an exit-code contract for CI.  The passes themselves live
in ``passes.py`` (rules GC01–GC05).

Design constraints:

- **stdlib only** — the CI graftcheck lane runs before any pip install,
  so nothing here (or in passes.py) may import jax, numpy, or the
  mxnet_tpu runtime.  Config knowledge (``config.KNOWN_VARS``) is read by
  *parsing* config.py, never importing it.
- **suppressions carry justifications** — ``# graftcheck: ignore[GC01] — why``
  on (or immediately above) the flagged line.  A bare ``ignore[...]``
  with no justification is itself a finding (GC00), so the suppression
  ledger stays reviewable.
- **exit codes**: 0 = clean (no unsuppressed findings), 1 = findings,
  2 = usage/internal error.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
import sys

__all__ = [
    "Finding", "ModuleInfo", "Context", "Pass", "PASSES", "register_pass",
    "parse_suppressions", "analyze_paths", "check_source", "main",
]

# --------------------------------------------------------------------------
# findings
# --------------------------------------------------------------------------


class Finding:
    """One rule violation at a source location."""

    __slots__ = ("rule", "path", "line", "message", "source_line")

    def __init__(self, rule, path, line, message, source_line=""):
        self.rule = rule
        self.path = path          # repo-relative posix path
        self.line = int(line)
        self.message = message
        self.source_line = source_line

    @property
    def fingerprint(self):
        """Content-addressed identity for baseline diffing: stable across
        unrelated edits that only shift line numbers."""
        text = self.source_line.strip() or f"line{self.line}"
        h = hashlib.sha1(
            f"{self.rule}|{self.path}|{text}".encode()).hexdigest()
        return h[:16]

    def to_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "fingerprint": self.fingerprint}

    def render(self):
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def __repr__(self):
        return f"<Finding {self.render()}>"


# --------------------------------------------------------------------------
# suppressions
# --------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*graftcheck:\s*ignore\[([A-Za-z0-9_,\s]+)\]\s*(?:[-—–:]+\s*(\S.*))?")
_COMMENT_ONLY_RE = re.compile(r"^\s*(#|$)")


def parse_suppressions(lines):
    """Map line number (1-based) -> (rules, justification, comment_line).

    A suppression on a code line applies to that line; on a comment-only
    line it applies to the next code line (stacked comment lines chain).
    A trailing suppression with no code line to govern is kept under the
    line past EOF so the hygiene checks still see it.
    """
    out = {}
    pending = []  # suppressions waiting for the next code line
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        entry = None
        if m:
            rules = frozenset(
                r.strip().upper() for r in m.group(1).split(",") if r.strip())
            entry = (rules, (m.group(2) or "").strip(), i)
        if _COMMENT_ONLY_RE.match(text):
            if entry:
                pending.append(entry)
            continue
        # a code line: attach its own inline suppression plus any pending
        here = list(pending)
        pending = []
        if entry:
            here.append(entry)
        if here:
            rules = frozenset().union(*(e[0] for e in here))
            just = "; ".join(e[1] for e in here if e[1])
            out[i] = (rules, just, here[0][2])
    if pending:
        # dangling at EOF: governs nothing, but must not vanish silently
        rules = frozenset().union(*(e[0] for e in pending))
        just = "; ".join(e[1] for e in pending if e[1])
        out[len(lines) + 1] = (rules, just, pending[0][2])
    return out


# --------------------------------------------------------------------------
# module / project context
# --------------------------------------------------------------------------


class ModuleInfo:
    """One parsed source file plus its suppression map."""

    def __init__(self, path, rel, text):
        self.path = path          # display path (repo-relative when known)
        self.rel = rel            # path relative to the package root, posix
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.suppressions = parse_suppressions(self.lines)

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule, node_or_line, message):
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(rule, self.path, line, message, self.line_text(line))


class Context:
    """Project-wide state shared by passes: every module, plus the repo /
    package roots so cross-file rules (knob catalog vs README) can see
    both sides."""

    def __init__(self, modules, package_root=None, repo_root=None):
        self.modules = modules
        self.package_root = package_root
        self.repo_root = repo_root

    def module(self, rel):
        for m in self.modules:
            if m.rel == rel:
                return m
        return None

    def read_repo_file(self, name):
        if not self.repo_root:
            return None
        p = os.path.join(self.repo_root, name)
        try:
            with open(p, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None


# --------------------------------------------------------------------------
# pass registry
# --------------------------------------------------------------------------


class Pass:
    """Base class for one rule.  Subclasses set ``rule`` + ``summary`` and
    implement ``check_module`` (per file) and/or ``check_project``
    (cross-file, runs once with the full Context)."""

    rule = "GC00"
    summary = ""

    def check_module(self, module, ctx):  # noqa: ARG002
        return []

    def check_project(self, ctx):  # noqa: ARG002
        return []


PASSES: list = []


def register_pass(cls):
    """Decorator adding a Pass subclass to the registry (pluggable: any
    module imported before the run may register more)."""
    PASSES.append(cls())
    return cls


# --------------------------------------------------------------------------
# runner
# --------------------------------------------------------------------------


def _iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git", ".claude"))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def _package_rel(path):
    """Path of a module relative to its enclosing ``mxnet_tpu`` package
    (what HOT_PATHS / THREADED_MODULES key on); falls back to basename."""
    parts = os.path.abspath(path).replace(os.sep, "/").split("/")
    if "mxnet_tpu" in parts:
        idx = len(parts) - 1 - parts[::-1].index("mxnet_tpu")
        return "/".join(parts[idx + 1:])
    return parts[-1]


def load_module(path, repo_root=None):
    with open(path, encoding="utf-8") as f:
        text = f.read()
    display = path
    if repo_root:
        try:
            display = os.path.relpath(path, repo_root).replace(os.sep, "/")
        except ValueError:
            pass
    return ModuleInfo(display, _package_rel(path), text)


def _apply_suppressions(module, findings):
    """Split raw findings into (kept, suppressed) per the module's
    suppression map.  An unjustified suppression never suppresses (its
    GC00 comes from _check_suppression_rules, which sees every ignore
    whether or not a finding matched)."""
    kept, suppressed = [], []
    for f in findings:
        sup = module.suppressions.get(f.line)
        if sup and f.rule in sup[0] and sup[1]:
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed


def _check_suppression_rules(module, known_rules):
    """Hygiene over EVERY ignore[...] comment, matched or not: unknown
    rule ids are typos that disable nothing, and a missing justification
    is itself a finding — both keep the suppression ledger reviewable."""
    out = []
    seen = set()
    for line, (rules, just, at) in sorted(module.suppressions.items()):
        if at in seen:
            continue
        seen.add(at)
        for r in sorted(rules):
            if r not in known_rules:
                out.append(module.finding(
                    "GC00", at, f"unknown rule {r!r} in suppression "
                    f"(known: {', '.join(sorted(known_rules))})"))
        if not just:
            out.append(module.finding(
                "GC00", at,
                "suppression has no justification — write "
                f"'# graftcheck: ignore[{', '.join(sorted(rules))}] — "
                "why this is safe'"))
    return out


def analyze_paths(paths, repo_root=None):
    """Run every registered pass over ``paths``.

    Returns (findings, suppressed, modules) — findings are unsuppressed.
    """
    modules, errors = [], []
    for path in _iter_py_files(paths):
        try:
            modules.append(load_module(path, repo_root=repo_root))
        except SyntaxError as e:
            errors.append(Finding("GC00", path, e.lineno or 0,
                                  f"syntax error: {e.msg}"))
    package_root = None
    for m in modules:
        if m.rel == "config.py":
            package_root = os.path.dirname(os.path.abspath(
                os.path.join(repo_root or ".", m.path)))
    ctx = Context(modules, package_root=package_root, repo_root=repo_root)

    known_rules = {p.rule for p in PASSES} | {"GC00"}
    all_kept, all_suppressed = list(errors), []
    by_module = {id(m): [] for m in modules}
    for p in PASSES:
        for m in modules:
            for f in p.check_module(m, ctx):
                by_module[id(m)].append(f)
        for f in p.check_project(ctx):
            m = next((mm for mm in modules if mm.path == f.path), None)
            if m is not None:
                by_module[id(m)].append(f)
            else:
                all_kept.append(f)
    for m in modules:
        kept, suppressed = _apply_suppressions(m, by_module[id(m)])
        kept.extend(_check_suppression_rules(m, known_rules))
        all_kept.extend(kept)
        all_suppressed.extend(suppressed)
    all_kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return all_kept, all_suppressed, modules


def check_source(source, rel="module.py", path=None):
    """Test helper: run all passes over one in-memory source snippet as if
    it lived at ``rel`` inside the mxnet_tpu package.  Returns
    (findings, suppressed)."""
    module = ModuleInfo(path or rel, rel, source)
    ctx = Context([module])
    known_rules = {p.rule for p in PASSES} | {"GC00"}
    raw = []
    for p in PASSES:
        raw.extend(p.check_module(module, ctx))
        raw.extend(p.check_project(ctx))
    kept, suppressed = _apply_suppressions(module, raw)
    kept.extend(_check_suppression_rules(module, known_rules))
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return kept, suppressed


# --------------------------------------------------------------------------
# baseline
# --------------------------------------------------------------------------


def load_baseline(path):
    """Baseline as a MULTISET {(rule, path, fingerprint): count} —
    identical-text findings share a fingerprint, so each baseline entry
    must excuse exactly one occurrence or a copy-pasted new violation
    would hide behind an old one."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    counts: dict = {}
    for e in data.get("findings", []):
        k = (e["rule"], e["path"], e["fingerprint"])
        counts[k] = counts.get(k, 0) + 1
    return counts


def write_baseline(path, findings):
    data = {
        "comment": "graftcheck baseline — known findings new code is "
                   "diffed against; regenerate with --write-baseline",
        "findings": [
            {"rule": f.rule, "path": f.path, "fingerprint": f.fingerprint,
             "message": f.message}
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

_USAGE = """\
usage: graftcheck.py [paths ...] [options]

Repo-native static analysis: hot-path purity (GC01), retrace hazards
(GC02), env-knob hygiene (GC03), lock discipline (GC04), telemetry-flag
discipline (GC05).  Default path: the mxnet_tpu package next to tools/.

options:
  --json                 machine-readable findings on stdout
  --list-rules           print the rule table and exit
  --baseline FILE        ignore findings recorded in FILE (diff mode)
  --write-baseline FILE  write current findings to FILE and exit 0
  -q, --quiet            suppress the summary line
"""


def main(argv=None, repo_root=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = quiet = False
    baseline_path = write_baseline_path = None
    paths = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print(_USAGE)
            return 0
        if a == "--json":
            as_json = True
        elif a in ("-q", "--quiet"):
            quiet = True
        elif a == "--list-rules":
            for p in PASSES:
                print(f"{p.rule}  {p.summary}")
            return 0
        elif a == "--baseline":
            i += 1
            if i >= len(argv):
                print("--baseline needs a file", file=sys.stderr)
                return 2
            baseline_path = argv[i]
        elif a == "--write-baseline":
            i += 1
            if i >= len(argv):
                print("--write-baseline needs a file", file=sys.stderr)
                return 2
            write_baseline_path = argv[i]
        elif a.startswith("-"):
            print(f"unknown option {a!r}\n{_USAGE}", file=sys.stderr)
            return 2
        else:
            paths.append(a)
        i += 1

    if repo_root is None:
        repo_root = os.getcwd()
    if not paths:
        default = os.path.join(repo_root, "mxnet_tpu")
        if not os.path.isdir(default):
            print("no paths given and no ./mxnet_tpu found", file=sys.stderr)
            return 2
        paths = [default]
    for p in paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    try:
        findings, suppressed, modules = analyze_paths(paths,
                                                      repo_root=repo_root)
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"graftcheck internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if write_baseline_path:
        write_baseline(write_baseline_path, findings)
        if not quiet:
            print(f"wrote {len(findings)} finding(s) to "
                  f"{write_baseline_path}")
        return 0

    if baseline_path:
        try:
            base = load_baseline(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"cannot read baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        remaining, kept = dict(base), []
        for f in findings:
            k = (f.rule, f.path, f.fingerprint)
            if remaining.get(k):
                remaining[k] -= 1  # each entry excuses ONE occurrence
            else:
                kept.append(f)
        findings = kept

    if as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "suppressed": len(suppressed),
            "files": len(modules),
        }, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        if not quiet:
            print(f"graftcheck: {len(findings)} finding(s), "
                  f"{len(suppressed)} suppressed, {len(modules)} file(s)"
                  + (" [vs baseline]" if baseline_path else ""))
    return 1 if findings else 0
