"""NDArray: MXNet's mutable async tensor, rebuilt over immutable jax.Arrays.

Reference anchors (SURVEY §2 N3, §7.1): include/mxnet/ndarray.h :: class
NDArray — ref-counted Chunk (storage + engine var), views (Slice/Reshape/At),
WaitToRead/WaitToWrite, Save/Load, autograd entry hooks;
python/mxnet/ndarray/ndarray.py — the Python surface.

TPU-native design — the **versioned slot**:
 - an NDArray owns a ``_Slot`` holding one immutable ``jax.Array`` plus a
   version counter.  "In-place" operations (``a[:]=``, ``+=``, optimizer
   updates, ``kv.pull(out=)``) swap the slot's array for a new functional
   value and bump the version.  Read-after-write ordering across aliases is
   then by construction: every read resolves the slot at call time, and JAX's
   async dispatch (the engine, see mxnet_tpu.engine) orders device work by
   data dependence.
 - **views** (basic-index slices, reshape) carry ``(base, spec)`` instead of
   data; reads re-slice the base's current value lazily, writes write back
   through the chain with ``x.at[idx].set`` — no index composition needed, and
   aliasing stays exact through arbitrarily nested views.
 - under ``autograd.record()``, slicing returns a *recorded copy* instead of a
   view (functional semantics on the tape) and in-place writes to arrays that
   participate in grad raise — the reference imposes the same restriction on
   recorded arrays.
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError, dtype_from_any, mx_real_t
from ..context import Context, current_context
from .. import engine as _engine

from contextlib import contextmanager


@contextmanager
def swap_slot_values(pairs):
    """Temporarily point NDArray slots at traced values; restore on exit.

    ``pairs`` — iterable of (NDArray, new_jax_value).  Yields the saved
    ``[(slot, old_value), ...]`` list so callers can diff old-vs-current to
    detect in-trace mutation.  This is THE tracing discipline shared by
    CachedOp (gluon/block.py), TrainStep (parallel.py) and the pipeline
    stage bridge (pipeline.py): trace a stateful imperative program as a
    pure function of its parameter values.  Restores raw slot values only —
    deliberately bypasses version bumps, since the swap must be invisible
    to the host-side engine ledger.
    """
    pairs = list(pairs)
    saved = [(nd_arr._slot, nd_arr._slot.value) for nd_arr, _ in pairs]
    try:
        for nd_arr, val in pairs:
            nd_arr._slot.value = val
        yield saved
    finally:
        for slot, old in saved:
            slot.value = old


__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concat", "save", "load", "waitall", "from_numpy", "from_dlpack"]


def _jnp():
    import jax.numpy as jnp
    return jnp


class _Slot:
    __slots__ = ("value", "version")

    def __init__(self, value):
        self.value = value
        self.version = 0


def _ctx_of_array(arr):
    try:
        dev = arr.device
        if dev is None:
            return current_context()
        if dev.platform == "cpu":
            return Context("cpu", dev.id)
        return Context("tpu", dev.id)
    except Exception:
        return current_context()


_BASIC_TYPES = (int, slice, type(None), type(Ellipsis))


def _is_basic_index(key):
    if isinstance(key, _BASIC_TYPES):
        return True
    if isinstance(key, tuple):
        return all(isinstance(k, _BASIC_TYPES) for k in key)
    return False


class NDArray:
    __slots__ = ("_slot", "_base", "_view", "_shape_cache", "_node", "_grad",
                 "grad_req", "_grad_epoch", "_ctx", "__weakref__")
    __array_priority__ = 1000.0

    # -- construction ---------------------------------------------------------
    def __init__(self):
        self._slot = None
        self._base = None
        self._view = None
        self._shape_cache = None
        self._node = None
        self._grad = None
        self.grad_req = "null"
        self._grad_epoch = -1
        self._ctx = None

    @classmethod
    def _from_data(cls, arr, ctx=None):
        self = cls()
        self._slot = _Slot(arr)
        self._ctx = ctx if ctx is not None else _ctx_of_array(arr)
        return self

    @classmethod
    def _make_view(cls, base, view_spec, shape):
        self = cls()
        self._base = base
        self._view = view_spec
        self._shape_cache = shape
        self._ctx = base._ctx
        return self

    # -- data access (the versioned-slot read/write protocol) -----------------
    @property
    def _data(self):
        if self._base is None:
            return self._slot.value
        kind, spec = self._view
        bv = self._base._data
        if kind == "index":
            return bv[spec]
        return bv.reshape(spec)  # kind == "reshape"

    def _set_data(self, arr):
        """Full overwrite of this array's (or view region's) value."""
        if self._base is None:
            self._slot.value = arr
            self._slot.version += 1
            return
        kind, spec = self._view
        if kind == "index":
            self._base._update_region(spec, arr)
        else:  # reshape view: push the whole buffer back through
            self._base._set_data(arr.reshape(self._base.shape))

    def _update_region(self, idx, value):
        if self._base is None:
            self._slot.value = self._slot.value.at[idx].set(value)
            self._slot.version += 1
        else:
            cur = self._data
            self._set_data(cur.at[idx].set(value))

    def _check_writable(self):
        from .. import autograd
        if autograd.is_recording() and (self._node is not None
                                        or (self._base is not None
                                            and self._base._node is not None)):
            raise MXNetError(
                "in-place write to an array that is part of a recorded "
                "computation is not allowed inside autograd.record() "
                "(reference contract: mutating recorded arrays invalidates "
                "the tape)")

    # -- basic properties -----------------------------------------------------
    @property
    def shape(self):
        if self._base is None:
            return tuple(self._slot.value.shape)
        return self._shape_cache

    @property
    def dtype(self):
        if self._base is None:
            return _np.dtype(self._slot.value.dtype)
        return self._base.dtype

    @property
    def size(self):
        s = 1
        for d in self.shape:
            s *= d
        return s

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def ctx(self):
        return self._ctx

    context = ctx

    @property
    def stype(self):
        return "default"

    @property
    def grad(self):
        return self._grad

    @property
    def handle(self):
        return self  # ABI-handle parity shim

    # -- sync points ----------------------------------------------------------
    def wait_to_read(self):
        import jax
        jax.block_until_ready(self._data)

    wait_to_write = wait_to_read

    def asnumpy(self):
        return _np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # -- autograd -------------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):  # noqa: ARG002
        self._node = None  # attach_grad detaches (reference semantics)
        self.grad_req = grad_req
        self._grad = zeros(self.shape, dtype=self.dtype, ctx=self.ctx)

    def _accumulate_grad(self, g):
        from .. import autograd
        if self._grad is None or self.grad_req == "null":
            return
        ep = autograd._current_epoch()
        if autograd._st().create_graph_mode and isinstance(g, NDArray):
            # higher-order mode: the grad must carry its tape node, so the
            # buffer object itself is replaced (documented divergence: the
            # old ._grad buffer is not aliased in this mode)
            if self.grad_req == "write" and self._grad_epoch != ep:
                self._grad = g
            else:
                self._grad = self._grad + g
            self._grad_epoch = ep
            return
        if isinstance(g, NDArray):
            g = g._data
        if self.grad_req == "write" and self._grad_epoch != ep:
            self._grad._set_data(g)
        else:
            self._grad._set_data(self._grad._data + g)
        self._grad_epoch = ep

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], [out_grad], retain_graph=retain_graph,
                          train_mode=train_mode)

    def detach(self):
        out = NDArray._from_data(self._data, ctx=self.ctx)
        return out

    # -- device movement ------------------------------------------------------
    def as_in_context(self, ctx):
        if ctx == self.ctx:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def copyto(self, other):
        import jax
        if isinstance(other, NDArray):
            arr = jax.device_put(self._data, other.ctx.jax_device())
            other._set_data(arr)
            return other
        if isinstance(other, Context):
            arr = jax.device_put(self._data, other.jax_device())
            return NDArray._from_data(arr, ctx=Context(other))
        raise MXNetError(f"copyto does not support type {type(other)}")

    def copy(self):
        return NDArray._from_data(self._data, ctx=self.ctx)

    def astype(self, dtype, copy=True):
        dt = dtype_from_any(dtype)
        if not copy and dt == self.dtype:
            return self
        return self._op1("cast", dtype=dt)

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sp
        return _sp.cast_storage(self, stype)

    # -- op dispatch sugar ----------------------------------------------------
    def _op1(self, opname, **attrs):
        from ..ops import registry as _reg
        return _reg.invoke(_reg.get(opname), [self], attrs)

    def _op2(self, opname, other, scalar_op=None, reverse=False, **attrs):
        from ..ops import registry as _reg
        if isinstance(other, NDArray):
            ins = [other, self] if reverse else [self, other]
            return _reg.invoke(_reg.get(opname), ins, attrs)
        if isinstance(other, (int, float, bool, _np.generic)):
            a = dict(attrs)
            a["scalar"] = float(other)
            a["reverse"] = reverse
            return _reg.invoke(_reg.get(scalar_op or opname + "_scalar"),
                               [self], a)
        return NotImplemented

    # arithmetic — names match the reference's broadcast_* op family
    def __add__(self, o):
        return self._op2("broadcast_add", o, "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._op2("broadcast_sub", o, "_minus_scalar")

    def __rsub__(self, o):
        return self._op2("broadcast_sub", o, "_minus_scalar", reverse=True)

    def __mul__(self, o):
        return self._op2("broadcast_mul", o, "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._op2("broadcast_div", o, "_div_scalar")

    def __rtruediv__(self, o):
        return self._op2("broadcast_div", o, "_div_scalar", reverse=True)

    def __floordiv__(self, o):
        return self._op2("broadcast_floor_div", o, "_floor_div_scalar")

    def __rfloordiv__(self, o):
        return self._op2("broadcast_floor_div", o, "_floor_div_scalar", reverse=True)

    def __mod__(self, o):
        return self._op2("broadcast_mod", o, "_mod_scalar")

    def __rmod__(self, o):
        return self._op2("broadcast_mod", o, "_mod_scalar", reverse=True)

    def __pow__(self, o):
        return self._op2("broadcast_power", o, "_power_scalar")

    def __rpow__(self, o):
        return self._op2("broadcast_power", o, "_power_scalar", reverse=True)

    def __matmul__(self, o):
        return self._op2("matmul", o)

    def __neg__(self):
        return self._op1("negative")

    def __abs__(self):
        return self._op1("abs")

    # comparisons
    def __eq__(self, o):
        if o is None:
            return False
        r = self._op2("broadcast_equal", o, "_equal_scalar")
        return r

    def __ne__(self, o):
        if o is None:
            return True
        return self._op2("broadcast_not_equal", o, "_not_equal_scalar")

    def __lt__(self, o):
        return self._op2("broadcast_lesser", o, "_lesser_scalar")

    def __le__(self, o):
        return self._op2("broadcast_lesser_equal", o, "_lesser_equal_scalar")

    def __gt__(self, o):
        return self._op2("broadcast_greater", o, "_greater_scalar")

    def __ge__(self, o):
        return self._op2("broadcast_greater_equal", o, "_greater_equal_scalar")

    __hash__ = object.__hash__  # identity hash, reference parity

    # in-place ops always mutate the slot so every alias observes the write
    # (reference engine-ordered write); under recording, writes to arrays on
    # the tape raise, matching __setitem__.  If the *operand* was recorded,
    # the result's tape node is carried so gradient still flows through.
    def _iop(self, opname, scalar_op, other):
        from .. import autograd
        if autograd.is_recording():
            self._check_writable()
        res = self._op2(opname, other, scalar_op)
        self._set_data(res._data)
        if res._node is not None:
            self._node = res._node
        return self

    def __iadd__(self, o):
        return self._iop("broadcast_add", "_plus_scalar", o)

    def __isub__(self, o):
        return self._iop("broadcast_sub", "_minus_scalar", o)

    def __imul__(self, o):
        return self._iop("broadcast_mul", "_mul_scalar", o)

    def __itruediv__(self, o):
        return self._iop("broadcast_div", "_div_scalar", o)

    # -- indexing -------------------------------------------------------------
    def __getitem__(self, key):
        from .. import autograd
        if isinstance(key, NDArray):
            key = key._data
        if _is_basic_index(key):
            if autograd.is_recording():
                return self._op1("_slice_basic", key=_freeze_index(key))
            import jax
            shape = jax.eval_shape(lambda x: x[key],
                                   jax.ShapeDtypeStruct(self.shape, self.dtype)).shape
            return NDArray._make_view(self, ("index", key), tuple(shape))
        # advanced indexing → copy (reference semantics)
        data = self._data[_np.asarray(key) if isinstance(key, list) else key]
        return NDArray._from_data(data, ctx=self.ctx)

    def __setitem__(self, key, value):
        self._check_writable()
        if isinstance(key, NDArray):
            key = key._data
        if isinstance(value, NDArray):
            value = value._data
        elif isinstance(value, (_np.ndarray, list)):
            value = _jnp().asarray(value, dtype=self.dtype)
        if isinstance(key, slice) and key == slice(None) and not _np.isscalar(value):
            v = _jnp().broadcast_to(_jnp().asarray(value, dtype=self.dtype), self.shape)
            self._set_data(v)
        else:
            cur = self._data
            if isinstance(key, list):
                key = _np.asarray(key)
            self._set_data(cur.at[key].set(value))
        _engine.on_dispatch([self._data] if self._base is None else [])

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("The truth value of an NDArray with multiple elements "
                         "is ambiguous")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __repr__(self):
        try:
            data = str(self.asnumpy())
        except Exception as e:  # async error surfaces here, like the reference
            raise
        return f"\n{data}\n<NDArray {'x'.join(map(str, self.shape))} @{self.ctx}>"

    # -- shape manipulation (views) ------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        new_shape = _infer_reshape(self.shape, tuple(shape))
        from .. import autograd
        if autograd.is_recording():
            return self._op1("reshape", shape=new_shape)
        return NDArray._make_view(self, ("reshape", new_shape), new_shape)

    def reshape_like(self, other):
        return self.reshape(other.shape)

    @property
    def T(self):
        return self._op1("transpose")

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return self._op1("transpose", axes=axes if axes else None)

    def swapaxes(self, dim1, dim2):
        return self._op1("swapaxes", dim1=dim1, dim2=dim2)

    def flatten(self):
        return self.reshape((self.shape[0], -1) if self.ndim > 1 else (-1,))

    def expand_dims(self, axis):
        return self._op1("expand_dims", axis=axis)

    def squeeze(self, axis=None):
        return self._op1("squeeze", axis=axis)

    def broadcast_to(self, shape):
        return self._op1("broadcast_to", shape=tuple(shape))

    def broadcast_like(self, other):
        return self.broadcast_to(other.shape)

    def slice(self, begin, end, step=None):
        return self._op1("slice", begin=tuple(begin), end=tuple(end),
                         step=tuple(step) if step else None)

    def slice_axis(self, axis, begin, end):
        return self._op1("slice_axis", axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip"):
        from ..ops import registry as _reg
        return _reg.invoke(_reg.get("take"), [self, indices],
                           {"axis": axis, "mode": mode})

    def pick(self, index, axis=-1, keepdims=False):
        from ..ops import registry as _reg
        return _reg.invoke(_reg.get("pick"), [self, index],
                           {"axis": axis, "keepdims": keepdims})

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        return self._op1("one_hot", depth=depth, on_value=on_value,
                         off_value=off_value)

    # reductions / common math as methods (reference NDArray method surface)
    def _reduce(self, opname, axis=None, keepdims=False, **kw):
        return self._op1(opname, axis=_norm_axis(axis), keepdims=keepdims, **kw)

    def sum(self, axis=None, keepdims=False):
        return self._reduce("sum", axis, keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._reduce("mean", axis, keepdims)

    def max(self, axis=None, keepdims=False):
        return self._reduce("max", axis, keepdims)

    def min(self, axis=None, keepdims=False):
        return self._reduce("min", axis, keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._reduce("prod", axis, keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return self._op1("norm", ord=ord, axis=_norm_axis(axis), keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        return self._op1("argmax", axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        return self._op1("argmin", axis=axis, keepdims=keepdims)

    def abs(self):
        return self._op1("abs")

    def sqrt(self):
        return self._op1("sqrt")

    def square(self):
        return self._op1("square")

    def exp(self):
        return self._op1("exp")

    def log(self):
        return self._op1("log")

    def relu(self):
        return self._op1("relu")

    def sigmoid(self):
        return self._op1("sigmoid")

    def tanh(self):
        return self._op1("tanh")

    def softmax(self, axis=-1):
        return self._op1("softmax", axis=axis)

    def log_softmax(self, axis=-1):
        return self._op1("log_softmax", axis=axis)

    def clip(self, a_min, a_max):
        return self._op1("clip", a_min=a_min, a_max=a_max)

    def dot(self, other, **kw):
        from ..ops import registry as _reg
        return _reg.invoke(_reg.get("dot"), [self, other], kw)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return self._op1("topk", axis=axis, k=k, ret_typ=ret_typ,
                         is_ascend=is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        return self._op1("sort", axis=axis, is_ascend=is_ascend)

    def argsort(self, axis=-1, is_ascend=True):
        return self._op1("argsort", axis=axis, is_ascend=is_ascend)

    def tile(self, reps):
        return self._op1("tile", reps=tuple(reps) if not isinstance(reps, int) else (reps,))

    def repeat(self, repeats, axis=None):
        return self._op1("repeat", repeats=repeats, axis=axis)

    def flip(self, axis):
        return self._op1("flip", axis=axis)

    def zeros_like(self):
        return zeros(self.shape, dtype=self.dtype, ctx=self.ctx)

    def ones_like(self):
        return ones(self.shape, dtype=self.dtype, ctx=self.ctx)

    def as_np_ndarray(self):
        from .. import numpy as _mxnp
        return _mxnp.ndarray._as_np(self)

    def to_dlpack_for_read(self):
        return self._data.__dlpack__()

    to_dlpack_for_write = to_dlpack_for_read


def _freeze_index(key):
    """Make a basic index hashable for jit attr caching."""
    def f(k):
        if isinstance(k, slice):
            return ("slice", k.start, k.stop, k.step)
        if k is Ellipsis:
            return ("ellipsis",)
        if k is None:
            return ("newaxis",)
        return ("int", int(k))
    if isinstance(key, tuple):
        return ("tuple",) + tuple(f(k) for k in key)
    return f(key)


def _thaw_index(fk):
    def g(t):
        if t[0] == "slice":
            return slice(t[1], t[2], t[3])
        if t[0] == "ellipsis":
            return Ellipsis
        if t[0] == "newaxis":
            return None
        return t[1]
    if fk[0] == "tuple":
        return tuple(g(t) for t in fk[1:])
    return g(fk)


def _norm_axis(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


def _infer_reshape(old_shape, new_shape):
    """MXNet reshape special codes: 0 copy-dim, -1 infer, -2 copy-rest,
    -3 merge-two, -4 split (reference src/operator/tensor/matrix_op-inl.h)."""
    if all(isinstance(d, int) and d > 0 for d in new_shape):
        return tuple(new_shape)
    out = []
    src = list(old_shape)
    i = 0  # index into old dims
    j = 0
    ns = list(new_shape)
    while j < len(ns):
        d = ns[j]
        if d == 0:
            out.append(src[i]); i += 1
        elif d == -1:
            out.append(-1); i += 1
        elif d == -2:
            out.extend(src[i:]); i = len(src)
        elif d == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif d == -4:
            a, b = ns[j + 1], ns[j + 2]
            cur = src[i]
            if a == -1:
                a = cur // b
            if b == -1:
                b = cur // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(d); i += 1
        j += 1
    # resolve single -1 by element count
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in old_shape:
            total *= d
        out[out.index(-1)] = total // max(known, 1)
    return tuple(out)


# --------------------------------------------------------------------------
# creation
# --------------------------------------------------------------------------

def _put(np_arr, ctx):
    import jax
    ctx = ctx if ctx is not None else current_context()
    return jax.device_put(np_arr, ctx.jax_device()), ctx


def array(source_array, ctx=None, dtype=None):
    was_np = isinstance(source_array, (_np.ndarray, NDArray))
    if isinstance(source_array, NDArray):
        src = source_array.asnumpy()
    else:
        src = _np.asarray(source_array)
    if dtype is None:
        # reference default: python lists/scalars land as float32
        # (mx_real_t); numpy/NDArray sources keep their dtype — including
        # float64 (silent downcast would lose precision for porting users)
        dtype = src.dtype if was_np else mx_real_t
    src = src.astype(dtype_from_any(dtype), copy=False)
    arr, ctx = _put(src, ctx)
    return NDArray._from_data(arr, ctx=ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs):  # noqa: ARG001
    if isinstance(shape, int):
        shape = (shape,)
    import jax
    ctx = ctx if ctx is not None else current_context()
    with jax.default_device(ctx.jax_device()):
        arr = _jnp().zeros(tuple(shape), dtype_from_any(dtype))
    return NDArray._from_data(arr, ctx=ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):  # noqa: ARG001
    if isinstance(shape, int):
        shape = (shape,)
    import jax
    ctx = ctx if ctx is not None else current_context()
    with jax.default_device(ctx.jax_device()):
        arr = _jnp().ones(tuple(shape), dtype_from_any(dtype))
    return NDArray._from_data(arr, ctx=ctx)


def full(shape, val, ctx=None, dtype=None):
    if isinstance(shape, int):
        shape = (shape,)
    import jax
    ctx = ctx if ctx is not None else current_context()
    with jax.default_device(ctx.jax_device()):
        arr = _jnp().full(tuple(shape), val, dtype_from_any(dtype))
    return NDArray._from_data(arr, ctx=ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    import jax
    ctx = ctx if ctx is not None else current_context()
    with jax.default_device(ctx.jax_device()):
        arr = _jnp().arange(start, stop, step, dtype_from_any(dtype))
        if repeat != 1:
            arr = _jnp().repeat(arr, repeat)
    return NDArray._from_data(arr, ctx=ctx)


def concat(*arrays, dim=1):
    from ..ops import registry as _reg
    return _reg.invoke(_reg.get("concat"), list(arrays), {"dim": dim})


def from_numpy(a, zero_copy=False):  # noqa: ARG001
    return array(a)


def from_dlpack(capsule):
    import jax
    arr = jax.dlpack.from_dlpack(capsule)
    return NDArray._from_data(arr)


def waitall():
    _engine.waitall()


# --------------------------------------------------------------------------
# save / load — the `.params` role (reference src/ndarray/ndarray.cc ::
# NDArray::Save/Load via dmlc::Stream).  Container format here is a
# deterministic npz (documented divergence: reference byte format needs the
# C++ dmlc stream layout; API and filename conventions are preserved).
# --------------------------------------------------------------------------

_SAVE_MAGIC = "mxnet_tpu.params.v1"


def save(fname, data, format=None):  # noqa: A002 — reference-style kwarg
    """Save NDArrays (reference mx.nd.save → MXNDArraySave).

    format: 'dmlc' writes the reference's byte-compatible .params layout
    (dmlc_params.py) so files interchange with upstream MXNet; 'npz'
    (default) is this framework's richer container (sparse, bf16).
    MXNET_PARAMS_FORMAT flips the default.  ``load`` auto-detects both.
    """
    from .. import config as _cfg
    if format is None:
        format = _cfg.get("MXNET_PARAMS_FORMAT", "npz")
    if isinstance(data, NDArray):
        data = [data]
    if format == "dmlc":
        from .. import dmlc_params
        if isinstance(data, dict):
            names = list(data)
            arrays = [data[k].asnumpy() for k in names]
        elif isinstance(data, (list, tuple)):
            names, arrays = [], [v.asnumpy() for v in data]
        else:
            raise MXNetError("save expects NDArray, list or dict of NDArrays")
        with open(fname, "wb") as f:
            f.write(dmlc_params.save_bytes(arrays, names))
        return
    if format != "npz":
        raise MXNetError(f"unknown params format {format!r}: npz or dmlc")
    payload = {"__magic__": _np.frombuffer(_SAVE_MAGIC.encode(), dtype=_np.uint8)}
    if isinstance(data, dict):
        for k, v in data.items():
            payload["name:" + k] = v.asnumpy()
    elif isinstance(data, (list, tuple)):
        for i, v in enumerate(data):
            payload[f"idx:{i:08d}"] = v.asnumpy()
    else:
        raise MXNetError("save expects NDArray, list or dict of NDArrays")
    with open(fname, "wb") as f:
        _np.savez(f, **payload)


def load(fname, ctx=None):
    """Load NDArrays; auto-detects the reference dmlc .params byte format
    (files written by upstream mx.nd.save load directly) and the npz
    container."""
    with open(fname, "rb") as f:
        head = f.read(8)
    from .. import dmlc_params
    if dmlc_params.is_dmlc_params(head):
        with open(fname, "rb") as f:
            arrays, names = dmlc_params.load_bytes(f.read())
        if names:
            return {n: array(a, ctx=ctx) for n, a in zip(names, arrays)}
        return [array(a, ctx=ctx) for a in arrays]
    with _np.load(fname, allow_pickle=False) as z:
        keys = [k for k in z.files if k != "__magic__"]
        if keys and keys[0].startswith("name:"):
            return {k[len("name:"):]: array(z[k], ctx=ctx) for k in sorted(keys)}
        return [array(z[k], ctx=ctx) for k in sorted(keys)]
