"""Generate the ``mx.nd.*`` namespaces from the operator registry.

Rebuild of python/mxnet/ndarray/register.py :: _make_ndarray_function — the
reference introspects the nnvm registry via MXSymbolGetAtomicSymbolInfo and
writes Python functions at import; we do the same against
mxnet_tpu.ops.registry.  Dotted op names become sub-namespaces
(``random.uniform`` → ``mx.nd.random.uniform``) plus flattened aliases
(``random_uniform``), matching the reference's dual exposure.
"""

from __future__ import annotations

import sys
import types

import numpy as _np

from ..ops import registry as _reg
from .ndarray import NDArray, array as _array


def _make_op_func(op):
    def fn(*args, out=None, name=None, ctx=None, **attrs):  # noqa: ARG001
        inputs = []
        for a in args:
            if isinstance(a, NDArray):
                inputs.append(a)
            elif isinstance(a, _np.ndarray):
                inputs.append(_array(a, ctx=ctx))
            elif a is None:
                continue
            else:
                raise TypeError(
                    f"operator {op.name}: positional arguments must be "
                    f"NDArray (got {type(a).__name__}); pass scalars as "
                    "keyword attributes")
        return _reg.invoke(op, inputs, attrs, out=out, ctx=ctx)

    fn.__name__ = op.name.split(".")[-1]
    fn.__doc__ = op.doc or f"auto-generated wrapper for operator {op.name!r}"
    return fn


def populate(target_module, prefix=""):
    """Install generated functions into target_module.

    Existing attributes are never overwritten (hand-written helpers win).
    Returns the list of names installed.
    """
    installed = []
    submodules = {}
    for name in _reg.list_ops():
        if prefix:
            if not name.startswith(prefix + "."):
                continue
            local = name[len(prefix) + 1:]
        else:
            local = name
        fn = _make_op_func(_reg.get(name))
        if "." in local:
            ns, leaf = local.split(".", 1)
            if "." in leaf:
                continue  # only one level of nesting in the reference
            if ns not in submodules:
                modname = f"{target_module.__name__}.{ns}"
                mod = sys.modules.get(modname)
                if mod is None:
                    mod = types.ModuleType(
                        modname, f"generated operator namespace {ns!r}")
                    sys.modules[modname] = mod
                if not hasattr(target_module, ns):
                    setattr(target_module, ns, mod)
                submodules[ns] = getattr(target_module, ns)
            sub = submodules[ns]
            if not hasattr(sub, leaf):
                setattr(sub, leaf, fn)
                installed.append(f"{ns}.{leaf}")
            flat = local.replace(".", "_")
            if not hasattr(target_module, flat):
                setattr(target_module, flat, fn)
                installed.append(flat)
        else:
            if not hasattr(target_module, local):
                setattr(target_module, local, fn)
                installed.append(local)
    return installed
