"""Sparse NDArrays: row_sparse and CSR.

Rebuild of src/ndarray (NDArrayStorageType kRowSparseStorage/kCSRStorage) and
python/mxnet/ndarray/sparse.py.  TPU-native design: a sparse array is a pair
of dense jax buffers ((indices, values) / (indptr, indices, data)) with the
NDArray op surface; kernels lower to gather/scatter/segment ops which XLA
vectorizes.  Used by the sparse-embedding / PS path (SURVEY §2.4 row
"Sparse / large-embedding sharding", BASELINE config 4).
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray, array as _dense_array, zeros as _dense_zeros

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "cast_storage", "zeros",
           "dot", "square_sum", "sparse_retain"]


def _jnp():
    import jax.numpy as jnp
    return jnp


class BaseSparseNDArray:
    def __init__(self, shape, ctx=None, dtype=None):
        self._shape = tuple(shape)
        self._ctx = ctx if ctx is not None else current_context()
        self._dtype = _np.dtype(dtype if dtype is not None else _np.float32)

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def ctx(self):
        return self._ctx

    context = ctx

    @property
    def size(self):
        s = 1
        for d in self._shape:
            s *= d
        return s

    def asnumpy(self):
        return self.tostype("default").asnumpy()

    def wait_to_read(self):
        pass

    def __repr__(self):
        return (f"\n<{type(self).__name__} "
                f"{'x'.join(map(str, self.shape))} @{self.ctx}>")


class RowSparseNDArray(BaseSparseNDArray):
    """(indices, values): values[i] is the dense row indices[i]; all other
    rows are zero.  reference: kRowSparseStorage, gradients of Embedding/dot
    and the PS sharded-embedding path."""

    def __init__(self, data, indices, shape, ctx=None, dtype=None):
        dtype = dtype if dtype is not None else getattr(data, "dtype", None)
        super().__init__(shape, ctx, dtype)
        self.data = data if isinstance(data, NDArray) else _dense_array(data, ctx=ctx)
        self.indices = indices if isinstance(indices, NDArray) else \
            _dense_array(_np.asarray(indices, dtype=_np.int64), ctx=ctx)

    @property
    def stype(self):
        return "row_sparse"

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype != "default":
            raise MXNetError(f"cannot convert row_sparse to {stype}")
        jnp = _jnp()
        dense = jnp.zeros(self._shape, self._dtype)
        idx = self.indices._data.astype(jnp.int32)
        dense = dense.at[idx].set(self.data._data)
        return NDArray._from_data(dense, ctx=self.ctx)

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._set_data(self.tostype("default")._data)
            return other
        return RowSparseNDArray(self.data.copy(), self.indices.copy(),
                                self._shape, ctx=other, dtype=self._dtype)

    def retain(self, row_ids):
        """sparse_retain: keep only the listed rows (reference
        src/operator/tensor/sparse_retain.cc)."""
        jnp = _jnp()
        rid = row_ids._data.astype(jnp.int64) if isinstance(row_ids, NDArray) \
            else jnp.asarray(row_ids, jnp.int64)
        mask = jnp.isin(self.indices._data, rid)
        keep = _np.nonzero(_np.asarray(mask))[0]
        return RowSparseNDArray(
            NDArray._from_data(self.data._data[keep]),
            NDArray._from_data(self.indices._data[keep]),
            self._shape, ctx=self.ctx, dtype=self._dtype)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            return self.tostype("default") + other.tostype("default")
        return self.tostype("default") + other


class CSRNDArray(BaseSparseNDArray):
    def __init__(self, data, indptr, indices, shape, ctx=None, dtype=None):
        dtype = dtype if dtype is not None else getattr(data, "dtype", None)
        super().__init__(shape, ctx, dtype)
        self.data = data if isinstance(data, NDArray) else _dense_array(data, ctx=ctx)
        self.indptr = indptr if isinstance(indptr, NDArray) else \
            _dense_array(_np.asarray(indptr, dtype=_np.int64), ctx=ctx)
        self.indices = indices if isinstance(indices, NDArray) else \
            _dense_array(_np.asarray(indices, dtype=_np.int64), ctx=ctx)

    @property
    def stype(self):
        return "csr"

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype != "default":
            raise MXNetError(f"cannot convert csr to {stype}")
        jnp = _jnp()
        indptr = _np.asarray(self.indptr._data)
        rows = _np.repeat(_np.arange(self._shape[0]), _np.diff(indptr))
        dense = jnp.zeros(self._shape, self._dtype)
        dense = dense.at[jnp.asarray(rows),
                         self.indices._data.astype(jnp.int32)].set(self.data._data)
        return NDArray._from_data(dense, ctx=self.ctx)

    def dot(self, dense):
        """csr @ dense — the registry SpMM kernel (``_sparse_dot_csr``:
        gather + segment-sum, differentiable, jits with static shapes)."""
        return dot(self, dense)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(data, indices, shape, ctx=ctx, dtype=dtype)
    if isinstance(arg1, NDArray):
        return row_sparse_view(arg1, ctx=ctx, dtype=dtype)
    dense = _np.asarray(arg1)
    nz = _np.where(_np.any(dense != 0, axis=tuple(range(1, dense.ndim))))[0]
    return RowSparseNDArray(dense[nz], nz.astype(_np.int64),
                            dense.shape, ctx=ctx, dtype=dtype or dense.dtype)


def row_sparse_view(dense_nd, ctx=None, dtype=None):
    """Compress a dense NDArray's nonzero ROWS into a RowSparseNDArray
    without round-tripping the full buffer through the host: the row mask
    reduces ON DEVICE (transfer = one bool per row), only the kept rows
    are gathered (on device).  This is what Embedding(sparse_grad=True)'s
    grad view uses — a (vocab, dim) gradient moves dim*touched floats,
    not the whole table.

    Despite the name (kept for the reference's grad-stype API surface),
    the result is a SNAPSHOT taken at call time, not a live view: the
    mask/indices are materialized per call and mutations to the returned
    RowSparseNDArray do NOT flow back into the dense buffer.  Callers on
    the reference's grad-stype path must re-fetch after each backward."""
    jnp = _jnp()
    gd = dense_nd._data
    mask = _np.asarray(jnp.any(gd != 0,
                               axis=tuple(range(1, gd.ndim))))  # (rows,)
    idx = _np.nonzero(mask)[0]
    vals = gd[jnp.asarray(idx)]                    # device gather
    return RowSparseNDArray(NDArray._from_data(vals),
                            idx.astype(_np.int64), dense_nd.shape,
                            ctx=ctx or dense_nd.ctx,
                            dtype=dtype or dense_nd.dtype)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(data, indptr, indices, shape, ctx=ctx, dtype=dtype)
    dense = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1)
    rows, cols = _np.nonzero(dense)
    data = dense[rows, cols]
    indptr = _np.zeros(dense.shape[0] + 1, dtype=_np.int64)
    for r in rows:
        indptr[r + 1] += 1
    indptr = _np.cumsum(indptr)
    return CSRNDArray(data, indptr, cols.astype(_np.int64), dense.shape,
                      ctx=ctx, dtype=dtype or dense.dtype)


def cast_storage(arr, stype):
    """reference src/operator/tensor/cast_storage.cc."""
    if stype == "default":
        return arr.tostype("default") if not isinstance(arr, NDArray) else arr
    if isinstance(arr, NDArray):
        if stype == "row_sparse":
            return row_sparse_array(arr, ctx=arr.ctx, dtype=arr.dtype)
        if stype == "csr":
            return csr_matrix(arr, ctx=arr.ctx, dtype=arr.dtype)
    raise MXNetError(f"cast_storage: unsupported target {stype}")


def dot(lhs, rhs, transpose_a=False):
    """Storage-aware dot (reference src/operator/tensor/dot.cc FComputeEx
    paths): csr @ dense and csr.T @ dense route to the registry kernel
    ``_sparse_dot_csr`` (gather + segment-sum SpMM, differentiable in the
    csr values and the dense operand); dense inputs fall back to nd.dot.
    """
    from .. import nd as _nd
    if isinstance(lhs, CSRNDArray):
        if not isinstance(rhs, NDArray):
            raise MXNetError("sparse.dot: rhs must be a dense NDArray")
        return _nd._sparse_dot_csr(lhs.data, lhs.indptr, lhs.indices,
                                   rhs, transpose_a=transpose_a,
                                   num_cols=lhs.shape[1])
    if isinstance(lhs, RowSparseNDArray):
        return _nd.dot(lhs.tostype("default"), rhs,
                       transpose_a=transpose_a)
    return _nd.dot(lhs, rhs, transpose_a=transpose_a)


def square_sum(rsp, axis=None, keepdims=False):
    """Sum of squares over a row_sparse array touching only stored rows
    (reference square_sum.cc — used by lazy-update optimizers)."""
    from .. import nd as _nd
    if not isinstance(rsp, RowSparseNDArray):
        raise MXNetError("square_sum expects a RowSparseNDArray")
    return _nd._square_sum_rs(rsp.data, rsp.indices,
                              num_rows=rsp.shape[0], axis=axis,
                              keepdims=keepdims)


def sparse_retain(rsp, row_ids):
    """Functional sparse_retain (reference sparse_retain.cc): keep only
    the listed rows.  Values flow through the differentiable ``take``
    registry op (its backward scatters grads exactly to the kept slots);
    only the slot compaction — a data-dependent SIZE, inherently
    host-side — runs in numpy.  The standalone masking kernel
    ``_sparse_retain_values`` (same-shape zeroing) remains available for
    callers that need static shapes under jit."""
    from .. import nd as _nd
    if not isinstance(rsp, RowSparseNDArray):
        raise MXNetError("sparse_retain expects a RowSparseNDArray")
    rid = row_ids._data if isinstance(row_ids, NDArray) \
        else _jnp().asarray(_np.asarray(row_ids, _np.int64))
    jnp = _jnp()
    keep = _np.nonzero(_np.asarray(
        jnp.isin(rsp.indices._data,
                 rid.astype(rsp.indices._data.dtype))))[0]
    keep_nd = _dense_array(keep.astype(_np.int64))
    kept_vals = _nd.take(rsp.data, keep_nd, axis=0)
    return RowSparseNDArray(
        kept_vals,
        NDArray._from_data(rsp.indices._data[jnp.asarray(keep)]),
        rsp.shape, ctx=rsp.ctx, dtype=rsp.dtype)


def zeros(stype, shape, ctx=None, dtype=None):
    if stype == "default":
        return _dense_zeros(shape, ctx=ctx, dtype=dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(_np.zeros((0,) + tuple(shape[1:])),
                                _np.zeros((0,), _np.int64), shape, ctx, dtype)
    if stype == "csr":
        return CSRNDArray(_np.zeros((0,)), _np.zeros(shape[0] + 1, _np.int64),
                          _np.zeros((0,), _np.int64), shape, ctx, dtype)
    raise MXNetError(f"unknown stype {stype}")
