"""mx.nd — the imperative NDArray API (reference python/mxnet/ndarray/)."""

from .ndarray import (  # noqa: F401
    NDArray, array, zeros, ones, full, empty, arange, concat, save, load,
    waitall, from_numpy, from_dlpack,
)

import sys as _sys

from . import register as _register

# generate mx.nd.<op> namespaces from the registry (reference parity:
# python/mxnet/ndarray/register.py runs at import)
_GENERATED = _register.populate(_sys.modules[__name__])

from . import sparse  # noqa: F401,E402
from .sparse import cast_storage  # noqa: F401,E402  (reference nd.cast_storage)


def imresize(*args, **kwargs):
    from ..image import imresize as _f
    return _f(*args, **kwargs)


def Custom(*inputs, op_type=None, **kwargs):
    """User-registered python op (reference mx.nd.Custom → custom.cc).

    See mxnet_tpu.operator for the CustomOp/CustomOpProp registration
    surface; under autograd the op's ``backward`` is the vjp."""
    from ..base import MXNetError
    if op_type is None:
        raise MXNetError("nd.Custom requires op_type=")
    from .. import operator as _op
    return _op.invoke_custom(list(inputs), op_type, **kwargs)
