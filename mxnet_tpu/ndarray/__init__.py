"""mx.nd — the imperative NDArray API (reference python/mxnet/ndarray/)."""

from .ndarray import (  # noqa: F401
    NDArray, array, zeros, ones, full, empty, arange, concat, save, load,
    waitall, from_numpy, from_dlpack,
)

import sys as _sys

from . import register as _register

# generate mx.nd.<op> namespaces from the registry (reference parity:
# python/mxnet/ndarray/register.py runs at import)
_GENERATED = _register.populate(_sys.modules[__name__])

from . import sparse  # noqa: F401,E402


def imresize(*args, **kwargs):
    from ..image import imresize as _f
    return _f(*args, **kwargs)
