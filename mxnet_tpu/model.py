"""Legacy model helpers (reference python/mxnet/model.py): save_checkpoint /
load_checkpoint (the symbol-json + .params interchange pair, SURVEY §5.4) and
the FeedForward shim."""

from __future__ import annotations

from .base import MXNetError
from . import ndarray as nd

__all__ = ["save_checkpoint", "load_checkpoint", "load_params", "FeedForward",
           "BatchEndParam"]

from collections import namedtuple

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):  # noqa: ARG001
    """prefix-symbol.json + prefix-####.params (reference Module/model)."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(prefix, epoch):
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1) if ":" in k else ("arg", k)
        if tp == "arg":
            arg_params[name] = v
        else:
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    from . import symbol as sym
    symbol = sym.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class FeedForward:
    """Deprecated-in-reference training wrapper; kept as a thin veneer over
    Module for script compatibility."""

    def __init__(self, symbol, ctx=None, num_epoch=None, optimizer="sgd",
                 initializer=None, arg_params=None, aux_params=None,
                 **kwargs):
        from .module import Module
        self.symbol = symbol
        self._mod = Module(symbol, context=ctx)
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.kwargs = kwargs

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            batch_end_callback=None, epoch_end_callback=None, **kwargs):  # noqa: ARG002
        self._mod.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                      num_epoch=self.num_epoch or 1,
                      optimizer=self.optimizer,
                      batch_end_callback=batch_end_callback,
                      epoch_end_callback=epoch_end_callback,
                      initializer=self.initializer)

    def predict(self, X, num_batch=None):
        return self._mod.predict(X, num_batch=num_batch)

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, **kwargs)

    def save(self, prefix, epoch=0):
        arg, aux = self._mod.get_params()
        save_checkpoint(prefix, epoch, self.symbol, arg, aux)
