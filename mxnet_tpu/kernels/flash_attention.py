"""FlashAttention-2 for TPU in Pallas — forward + full custom backward.

Blockwise-softmax attention with O(L) memory: probabilities never
materialize in HBM (SURVEY §5.7; replaces the reference's full
softmax(QK^T) path in src/operator/contrib/transformer.cc).  Written
in-house rather than wrapping jax.experimental's kernel because this
framework runs with jax_enable_x64 on (MXNet float64 parity) and the
upstream kernel's index arithmetic miscompiles under x64 — everything
here pins explicit int32/float32 types, including BlockSpec index-map
literals (see ``_zi``).  This kernel is the TPU branch of
``contrib.masked_selfatt`` / ``contrib.masked_att_qkv``
(``ops/contrib.py::_attend``), gated by a one-time compile probe that
falls back to the dense fp32 path on toolchains that reject the IR.

Layout: q, k, v are (batch, heads, seq, head_dim); segment ids are
(batch, seq) int32 — attention only flows between positions with EQUAL
segment ids (padding mask: valid tokens segment 1, pad tokens 0).

Grid design (canonical TPU flash schedule, head-blocked): grid
(B, n_h, n_q, n_kv) with the kv dimension innermost — TPU grid steps run
sequentially per core, so the running (m, l, acc) live in VMEM scratch
across kv steps and the output block writes once on the last kv step.
Each step processes a BLOCK OF HEADS (block_h) at once via batched
dot_generals: with head_dim 64 a single-head (bq, 64) x (64, bk) matmul
underfills the MXU and the per-step fixed cost (grid loop + DMA
orchestration) dominates; batching heads divides the sequential step
count by block_h and amortizes that cost (measured ~2.5x over the
single-head schedule at BERT-base shapes).  All matmuls accumulate in
float32 on the MXU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_NEG_INF = -1e30
_M_FLOOR = -1e4  # running-max clamp: keeps exp(s - m) an exact 0.0 for
                 # masked entries (s = -1e30) without a second where pass,
                 # while any real logit above -1e4 is unaffected
_LANES = 128     # VPU lane width: per-row scalars are stored broadcast over lanes
_SUBLANES = 8    # min sublane count — kv segment ids ride a (8, bk) tile
_STAT = 8        # stored width of per-row stats (lse/delta): the kernels
                 # only read [:, :, :1], so a narrow stored broadcast cuts
                 # the (B, H, L, width) HBM read/write 16x vs full lanes
                 # (VMEM pads the lane dim either way)


def _zi():
    """int32 zero for BlockSpec index maps.  Under jax_enable_x64 (this
    framework's default, MXNet float64 parity) a literal ``0`` in an index
    map becomes an i64 constant that Mosaic fails to legalize
    ('func.return (i32, i32, i32, i64)'); an explicit int32 compiles."""
    return jnp.int32(0)


def _pick_block_h(H, bq, bk, single_tile=False):
    """Largest divisor of H whose f32 score tile (Hb, bq, bk) stays under
    the VMEM budget (the tile is the dominant scratch; Mosaic needs
    headroom for double-buffered input blocks).

    STREAMING grids keep the conservative ~1MB budget: the running
    (m, l, acc) scratch lives across kv steps on top of the score tile.
    The SINGLE-TILE kernels (whole seq in one block — no streaming
    scratch) afford more: measured on v5e at BERT-base seq-512 shapes,
    head-batching runs the fused fwd+bwd ~15-25% faster than hb=1 (4.4
    vs 4.9-5.9 ms/layer) by batching more head matmuls per grid step.
    Ceilings are asymmetric: the FWD single-tile kernel holds one
    (hb, bq, bk) f32 score tile (4MB budget → hb=4 at 512x512/12h);
    the fused BWD holds s/p/dp/ds simultaneously — hb=4 there needs
    16.3M scoped vmem against the 16.0M in-context limit (measured OOM
    inside the full train step), so bwd gets 3MB → hb=3."""
    if single_tile:   # knobs apply ONLY to the single-tile kernels — the
        # streaming grids carry running scratch the forced tile would blow
        from .. import config
        forced = config.get(
            "MXNET_FLASH_BLOCK_H_BWD" if single_tile == "bwd"
            else "MXNET_FLASH_BLOCK_H_FWD")
        if forced and H % int(forced) == 0:
            # non-divisor head counts FALL THROUGH to the auto pick (not an
            # error): the knob targets one model's shape, but the same
            # process also compiles other head counts — notably the
            # eligibility probe's small-H configs, which must keep passing
            # or the whole flash path silently degrades to dense
            return int(forced)
    if single_tile == "bwd":
        budget = 3 * 1024 * 1024
    elif single_tile:
        budget = 4 * 1024 * 1024
    else:
        budget = 1024 * 1024
    for hb in range(H, 0, -1):
        if H % hb == 0 and hb * bq * bk * 4 <= budget:
            return hb
    return 1


def _pick_block(L, want):
    """Largest of (want, 256, 128) that divides L — the seq block must
    tile L exactly or the grid silently drops rows."""
    for b in (want, 256, 128):
        if b <= L and L % b == 0:
            return b
    return L


def _mask_block(sq_ref, skv_ref, causal, iq, ik, bq, bk):
    """(bq, bk) bool mask for one tile, or None when the tile needs no
    masking at all (seg_q=None, non-causal — the static no-mask
    specialization: every mask construction + where pass vanishes from
    the compiled kernel).  int32 iota only (x64-safe).

    sq_ref block is (1, bq, LANES) (q ids broadcast over lanes), skv_ref is
    (1, SUBLANES, bk) (kv ids broadcast over sublanes) — the tile-legal
    layout trick for 1-per-row scalars."""
    mask = None
    if sq_ref is not None:
        sq = sq_ref[0][:, :1]      # (bq, 1)
        skv = skv_ref[0][:1, :]    # (1, bk)
        mask = sq == skv
    if causal:
        qi = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
        ki = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
        cm = qi >= ki
        mask = cm if mask is None else jnp.logical_and(mask, cm)
    return mask


def _mask_block_T(sqT_ref, skvT_ref, causal, iq, ik, bq, bk):
    """(bk, bq) mask (or None) — the TRANSPOSED tile for the dk/dv
    kernel, built directly from transposed segment layouts (sqT
    (1, SUBLANES, bq) q ids over lanes, skvT (1, bk, LANES) kv ids over
    sublanes) because Mosaic cannot legalize a bool vector transpose
    (`tpu.transpose` on i1)."""
    mask = None
    if sqT_ref is not None:
        sq = sqT_ref[0][:1, :]     # (1, bq)
        skv = skvT_ref[0][:, :1]   # (bk, 1)
        mask = skv == sq           # (bk, bq)
    if causal:
        ki = jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 0) + ik * bk
        qi = jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 1) + iq * bq
        cm = qi >= ki
        mask = cm if mask is None else jnp.logical_and(mask, cm)
    return mask


def _seg_row_layout(seg, L):
    """Segment ids per SUBLANE row — (B, L, _LANES), the tile-legal layout
    for q-side ids in (bq, bk) masks.  THE single definition of the
    layout trick; every kernel builder uses these helpers."""
    return jnp.broadcast_to(seg[:, :, None], (seg.shape[0], L, _LANES))


def _seg_lane_layout(seg, L):
    """Segment ids per LANE — (B, _SUBLANES, L), for kv-side ids in
    (bq, bk) masks and q-side ids in transposed (bk, bq) masks."""
    return jnp.broadcast_to(seg[:, None, :], (seg.shape[0], _SUBLANES, L))


def _apply_mask(s, mask):
    return s if mask is None else \
        jnp.where(mask[None], s, jnp.float32(_NEG_INF))


def _bmm(a, b, contract_a, contract_b):
    """Batched-over-heads MXU matmul: a (Hb, m, ca), b (Hb, n, cb) with the
    given contraction dims, f32 accumulation."""
    return jax.lax.dot_general(
        a, b, (((contract_a,), (contract_b,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, *rest, causal, scale, n_kv, has_seg):
    if has_seg:
        sq_ref, skv_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
        sq_ref = skv_ref = None
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _M_FLOOR)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal + whole q block above the diagonal => every entry masked:
    # skip the tile's compute entirely (the accumulators pass through)
    bq_, bk_ = q_ref.shape[2], k_ref.shape[2]
    live = jnp.bool_(True) if not causal \
        else (iq * bq_ + bq_ - 1 >= ik * bk_)

    @pl.when(live)
    def _tile():
        # scale is folded into q (a (Hb, bq, d) multiply) instead of into
        # the (Hb, bq, bk) score tile — the kernel is VPU-bound on tile-
        # sized elementwise passes, so every saved pass counts
        q = q_ref[0] * jnp.asarray(scale, q_ref.dtype)        # (Hb, bq, d)
        k = k_ref[0]                                          # (Hb, bk, d)
        v = v_ref[0]
        bq, bk = q.shape[1], k.shape[1]

        s = _bmm(q, k, 2, 2)                                  # (Hb, bq, bk)
        # NOTE a data-dependent uniform-tile fast path (skip the mask when
        # all segment ids in the tile agree) was measured SLOWER here —
        # the pl.when-branched body defeats Mosaic's grid pipelining.
        # The mask only vanishes via the STATIC specialization (seg=None)
        s = _apply_mask(s, _mask_block(sq_ref, skv_ref, causal, iq, ik,
                                       bq, bk))

        m_prev = m_scr[:, :, :1]                              # (Hb, bq, 1)
        l_prev = l_scr[:, :, :1]
        m_cur = jnp.max(s, axis=2, keepdims=True)             # (Hb, bq, 1)
        # the _M_FLOOR clamp makes exp(s - m_new) an exact 0.0 for masked
        # entries (s = -1e30) — no second where pass; fully-masked rows
        # keep l = 0 and are patched by safe_l in _finish
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                                # (Hb, bq, bk)
        alpha = jnp.exp(m_prev - m_new)                       # (Hb, bq, 1)
        l_new = l_prev * alpha + jnp.sum(p, axis=2, keepdims=True)
        acc = acc_scr[...] * alpha
        acc_scr[...] = acc + _bmm(p.astype(v.dtype), v, 2, 1)  # (Hb, bq, d)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == n_kv - 1)
    def _finish():
        l = l_scr[:, :, :1]
        safe_l = jnp.where(l == jnp.float32(0.0), jnp.float32(1.0), l)  # fully-masked rows
        o_ref[0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        lse = m_scr[:, :, :1] + jnp.log(safe_l)               # (Hb, bq, 1)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fwd_single_kernel(q_ref, k_ref, v_ref, *rest, causal, scale, has_seg):
    """Single-tile forward (n_q == n_kv == 1): direct softmax, no
    streaming scratch — the running-max/alpha machinery exists only to
    stitch kv blocks together."""
    if has_seg:
        sq_ref, skv_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
        sq_ref = skv_ref = None
    q = q_ref[0] * jnp.asarray(scale, q_ref.dtype)        # (Hb, bq, d)
    k = k_ref[0]
    v = v_ref[0]
    bq, bk = q.shape[1], k.shape[1]
    s = _bmm(q, k, 2, 2)                                  # (Hb, bq, bk)
    s = _apply_mask(s, _mask_block(sq_ref, skv_ref, causal,
                                   jnp.int32(0), jnp.int32(0), bq, bk))
    m = jnp.maximum(jnp.max(s, axis=2, keepdims=True),
                    jnp.float32(_M_FLOOR))                # (Hb, bq, 1)
    p = jnp.exp(s - m)            # masked: exp(-1e30 - m) == exact 0.0
    l = jnp.sum(p, axis=2, keepdims=True)
    safe_l = jnp.where(l == jnp.float32(0.0), jnp.float32(1.0), l)
    o_ref[0] = (_bmm(p.astype(v.dtype), v, 2, 1) / safe_l) \
        .astype(o_ref.dtype)
    lse = m + jnp.log(safe_l)
    lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fwd_single(q, k, v, seg_q, seg_kv, causal, scale, hb, interpret):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    n_h = H // hb
    has_seg = seg_q is not None
    spec_q = pl.BlockSpec((1, hb, Lq, D), lambda b, h: (b, h, _zi(), _zi()))
    spec_k = pl.BlockSpec((1, hb, Lk, D), lambda b, h: (b, h, _zi(), _zi()))
    in_specs = [spec_q, spec_k, spec_k]
    inputs = [q, k, v]
    if has_seg:
        in_specs += [
            pl.BlockSpec((1, Lq, _LANES), lambda b, h: (b, _zi(), _zi())),
            pl.BlockSpec((1, _SUBLANES, Lk),
                         lambda b, h: (b, _zi(), _zi())),
        ]
        inputs += [
            _seg_row_layout(seg_q, Lq),
            _seg_lane_layout(seg_kv, Lk),
        ]
    out, lse = pl.pallas_call(
        functools.partial(_fwd_single_kernel, causal=causal, scale=scale,
                          has_seg=has_seg),
        grid=(B, n_h),
        in_specs=in_specs,
        out_specs=[
            spec_q,
            pl.BlockSpec((1, hb, Lq, _STAT),
                         lambda b, h: (b, h, _zi(), _zi())),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Lq, _STAT), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    return out, lse[..., 0]


def _fwd(q, k, v, seg_q, seg_kv, causal, scale, block_q, block_k, block_h,
         interpret):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq, bk = _pick_block(Lq, block_q), _pick_block(Lk, block_k)
    single = Lq == bq and Lk == bk
    hb = block_h if block_h else _pick_block_h(H, bq, bk, single)
    if H % hb:
        raise ValueError(f"block_h={hb} must divide num heads {H} "
                         "(a partial head block would silently drop heads)")
    n_q, n_kv, n_h = Lq // bq, Lk // bk, H // hb
    if n_q == 1 and n_kv == 1:
        # whole sequence in one tile: direct-softmax kernel, no streaming
        return _fwd_single(q, k, v, seg_q, seg_kv, causal, scale, hb,
                           interpret)
    grid = (B, n_h, n_q, n_kv)
    has_seg = seg_q is not None
    in_specs = [
        pl.BlockSpec((1, hb, bq, D), lambda b, h, i, j: (b, h, i, _zi())),
        pl.BlockSpec((1, hb, bk, D), lambda b, h, i, j: (b, h, j, _zi())),
        pl.BlockSpec((1, hb, bk, D), lambda b, h, i, j: (b, h, j, _zi())),
    ]
    inputs = [q, k, v]
    if has_seg:
        seg_q = _seg_row_layout(seg_q, Lq)
        seg_kv = _seg_lane_layout(seg_kv, Lk)
        in_specs += [
            pl.BlockSpec((1, bq, _LANES), lambda b, h, i, j: (b, i, _zi())),
            pl.BlockSpec((1, _SUBLANES, bk), lambda b, h, i, j: (b, _zi(), j)),
        ]
        inputs += [seg_q, seg_kv]

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, scale=scale,
                          n_kv=n_kv, has_seg=has_seg),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, hb, bq, D), lambda b, h, i, j: (b, h, i, _zi())),
            pl.BlockSpec((1, hb, bq, _STAT),
                         lambda b, h, i, j: (b, h, i, _zi())),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Lq, _STAT), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((hb, bq, _LANES), jnp.float32),
            pltpu.VMEM((hb, bq, _LANES), jnp.float32),
            pltpu.VMEM((hb, bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    return out, lse[..., 0]  # lse (B, H, Lq)


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               *rest, causal, scale, n_kv, has_seg):
    if has_seg:
        sq_ref, skv_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
        sq_ref = skv_ref = None
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    bq_, bk_ = q_ref.shape[2], k_ref.shape[2]
    live = jnp.bool_(True) if not causal \
        else (iq * bq_ + bq_ - 1 >= ik * bk_)

    @pl.when(live)
    def _tile():
        # scale folded into the q load (s must match the fwd logits) and
        # into the dq finish below — never a (Hb, bq, bk) tile pass
        q = q_ref[0] * jnp.asarray(scale, q_ref.dtype)        # (Hb, bq, d)
        k = k_ref[0]                                          # (Hb, bk, d)
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)                    # (Hb, bq, d)
        lse = lse_ref[0][:, :, :1]                            # (Hb, bq, 1)
        delta = delta_ref[0][:, :, :1]                        # (Hb, bq, 1)
        bq, bk = q.shape[1], k.shape[1]

        s = _bmm(q, k, 2, 2)                                  # (Hb, bq, bk)
        s = _apply_mask(s, _mask_block(sq_ref, skv_ref, causal, iq, ik,
                                       bq, bk))
        p = jnp.exp(s - lse)          # masked entries: exp(-1e30 - lse) = 0
        dp = _bmm(do.astype(v.dtype), v, 2, 2)                # (Hb, bq, bk)
        ds = p * (dp - delta)         # ds * scale deferred to _finish
        dq_scr[...] += _bmm(ds.astype(k.dtype), k, 2, 1)      # (Hb, bq, d)

    @pl.when(ik == n_kv - 1)
    def _finish():
        dq_ref[0] = (dq_scr[...]
                     * jnp.float32(scale)).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                *rest, causal, scale, n_q, has_seg):
    if has_seg:
        sqT_ref, skvT_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
        sqT_ref = skvT_ref = None
    ik = pl.program_id(2)   # kv block: outer
    iq = pl.program_id(3)   # q block: inner (sequential accumulation)

    @pl.when(iq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    bq_, bk_ = q_ref.shape[2], k_ref.shape[2]
    live = jnp.bool_(True) if not causal \
        else (iq * bq_ + bq_ - 1 >= ik * bk_)

    @pl.when(live)
    def _tile():
        q = q_ref[0]                                          # (Hb, bq, d)
        qs = q * jnp.asarray(scale, q_ref.dtype)   # scaled copy: sT only —
        # dk below must use RAW q (its scale is applied once in _finish)
        k = k_ref[0]                                          # (Hb, bk, d)
        v = v_ref[0]
        do = do_ref[0]                                        # (Hb, bq, d)
        lse = lse_ref[0][:, :, 0][:, None, :]                 # (Hb, 1, bq)
        delta = delta_ref[0][:, :, 0][:, None, :]             # (Hb, 1, bq)
        bq, bk = q.shape[1], k.shape[1]

        sT = _bmm(k, qs, 2, 2)        # transposed tile: (Hb, bk, bq)
        sT = _apply_mask(sT, _mask_block_T(sqT_ref, skvT_ref, causal,
                                           iq, ik, bq, bk))
        pT = jnp.exp(sT - lse)        # masked entries -> exact 0.0
        dv_scr[...] += _bmm(pT.astype(do.dtype), do, 2, 1)    # (Hb, bk, d)
        dpT = _bmm(v, do, 2, 2)                               # (Hb, bk, bq)
        dsT = pT * (dpT - delta)      # dsT * scale deferred to _finish
        dk_scr[...] += _bmm(dsT.astype(q.dtype), q, 2, 1)     # (Hb, bk, d)

    @pl.when(iq == n_q - 1)
    def _finish():
        dk_ref[0] = (dk_scr[...]
                     * jnp.float32(scale)).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      *rest, causal, scale, has_seg):
    """Single-tile fused backward (n_q == n_kv == 1, i.e. seq <= block):
    dq, dk, dv from ONE pass — s and p computed once, dk/dv contract over
    the q dim (no transposes), inputs loaded once instead of twice.  The
    split dq/dkv kernels remain for multi-tile (long-seq) grids where
    dk/dv accumulation runs across q blocks."""
    if has_seg:
        sq_ref, skv_ref, dq_ref, dk_ref, dv_ref = rest
    else:
        dq_ref, dk_ref, dv_ref = rest
        sq_ref = skv_ref = None
    q = q_ref[0]                                          # (Hb, bq, d)
    qs = q * jnp.asarray(scale, q_ref.dtype)
    k = k_ref[0]                                          # (Hb, bk, d)
    v = v_ref[0]
    do = do_ref[0]                                        # (Hb, bq, d)
    lse = lse_ref[0][:, :, :1]                            # (Hb, bq, 1)
    delta = delta_ref[0][:, :, :1]                        # (Hb, bq, 1)
    bq, bk = q.shape[1], k.shape[1]

    s = _bmm(qs, k, 2, 2)                                 # (Hb, bq, bk)
    s = _apply_mask(s, _mask_block(sq_ref, skv_ref, causal,
                                   jnp.int32(0), jnp.int32(0), bq, bk))
    p = jnp.exp(s - lse)              # masked entries -> exact 0.0
    dp = _bmm(do.astype(v.dtype), v, 2, 2)                # (Hb, bq, bk)
    ds = p * (dp - delta)
    dq_ref[0] = (_bmm(ds.astype(k.dtype), k, 2, 1)
                 * jnp.float32(scale)).astype(dq_ref.dtype)
    # contract over bq (dim 1 of both operands): the transposed products
    # without any transpose op
    dv_ref[0] = _bmm(p.astype(do.dtype), do, 1, 1).astype(dv_ref.dtype)
    dk_ref[0] = (_bmm(ds.astype(q.dtype), q, 1, 1)
                 * jnp.float32(scale)).astype(dk_ref.dtype)


def _bwd_fused(q, k, v, seg_q, seg_kv, lse_b, delta_b, do, causal, scale,
               hb, interpret):
    """pallas_call wrapper for the single-tile fused backward."""
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    n_h = H // hb
    has_seg = seg_q is not None
    spec_q = pl.BlockSpec((1, hb, Lq, D), lambda b, h: (b, h, _zi(), _zi()))
    spec_k = pl.BlockSpec((1, hb, Lk, D), lambda b, h: (b, h, _zi(), _zi()))
    spec_stat = pl.BlockSpec((1, hb, Lq, _STAT),
                             lambda b, h: (b, h, _zi(), _zi()))
    in_specs = [spec_q, spec_k, spec_k, spec_q, spec_stat, spec_stat]
    inputs = [q, k, v, do, lse_b, delta_b]
    if has_seg:
        in_specs += [
            pl.BlockSpec((1, Lq, _LANES), lambda b, h: (b, _zi(), _zi())),
            pl.BlockSpec((1, _SUBLANES, Lk),
                         lambda b, h: (b, _zi(), _zi())),
        ]
        inputs += [
            _seg_row_layout(seg_q, Lq),
            _seg_lane_layout(seg_kv, Lk),
        ]
    return pl.pallas_call(
        functools.partial(_bwd_fused_kernel, causal=causal, scale=scale,
                          has_seg=has_seg),
        grid=(B, n_h),
        in_specs=in_specs,
        out_specs=[spec_q, spec_k, spec_k],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(*inputs)


def _bwd(q, k, v, seg_q, seg_kv, out, lse, do, causal, scale,
         block_q, block_k, block_h, interpret):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq, bk = _pick_block(Lq, block_q), _pick_block(Lk, block_k)
    single = "bwd" if (Lq == bq and Lk == bk) else False
    hb = block_h if block_h else _pick_block_h(H, bq, bk, single)
    if H % hb:
        raise ValueError(f"block_h={hb} must divide num heads {H} "
                         "(a partial head block would silently drop heads)")
    n_q, n_kv, n_h = Lq // bq, Lk // bk, H // hb

    # delta_i = rowsum(dO * O): cheap elementwise reduce, XLA fuses it
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                                   # (B, H, Lq)
    lse_b = jnp.broadcast_to(lse[..., None], lse.shape + (_STAT,))
    delta_b = jnp.broadcast_to(delta[..., None], delta.shape + (_STAT,))
    has_seg = seg_q is not None

    if n_q == 1 and n_kv == 1:
        # whole sequence in one tile: fused dq/dk/dv kernel (one s + one
        # exp + shared loads; see _bwd_fused_kernel)
        return _bwd_fused(q, k, v, seg_q, seg_kv, lse_b, delta_b, do,
                          causal, scale, hb, interpret)

    dq_specs = [
        pl.BlockSpec((1, hb, bq, D), lambda b, h, i, j: (b, h, i, _zi())),
        pl.BlockSpec((1, hb, bk, D), lambda b, h, i, j: (b, h, j, _zi())),
        pl.BlockSpec((1, hb, bk, D), lambda b, h, i, j: (b, h, j, _zi())),
        pl.BlockSpec((1, hb, bq, D), lambda b, h, i, j: (b, h, i, _zi())),
        pl.BlockSpec((1, hb, bq, _STAT),
                     lambda b, h, i, j: (b, h, i, _zi())),
        pl.BlockSpec((1, hb, bq, _STAT),
                     lambda b, h, i, j: (b, h, i, _zi())),
    ]
    dq_inputs = [q, k, v, do, lse_b, delta_b]
    dkv_specs = [
        pl.BlockSpec((1, hb, bq, D), lambda b, h, j, i: (b, h, i, _zi())),
        pl.BlockSpec((1, hb, bk, D), lambda b, h, j, i: (b, h, j, _zi())),
        pl.BlockSpec((1, hb, bk, D), lambda b, h, j, i: (b, h, j, _zi())),
        pl.BlockSpec((1, hb, bq, D), lambda b, h, j, i: (b, h, i, _zi())),
        pl.BlockSpec((1, hb, bq, _STAT),
                     lambda b, h, j, i: (b, h, i, _zi())),
        pl.BlockSpec((1, hb, bq, _STAT),
                     lambda b, h, j, i: (b, h, i, _zi())),
    ]
    dkv_inputs = [q, k, v, do, lse_b, delta_b]
    if has_seg:
        # two layouts of each segment-id vector: per-sublane-row for the
        # dq kernel's (bq, bk) mask, per-lane for the dkv (bk, bq) mask
        seg_qr = _seg_row_layout(seg_q, Lq)
        seg_kvl = _seg_lane_layout(seg_kv, Lk)
        seg_qT = _seg_lane_layout(seg_q, Lq)
        seg_kvT = _seg_row_layout(seg_kv, Lk)
        dq_specs += [
            pl.BlockSpec((1, bq, _LANES), lambda b, h, i, j: (b, i, _zi())),
            pl.BlockSpec((1, _SUBLANES, bk),
                         lambda b, h, i, j: (b, _zi(), j)),
        ]
        dq_inputs += [seg_qr, seg_kvl]
        dkv_specs += [
            pl.BlockSpec((1, _SUBLANES, bq),
                         lambda b, h, j, i: (b, _zi(), i)),
            pl.BlockSpec((1, bk, _LANES), lambda b, h, j, i: (b, j, _zi())),
        ]
        dkv_inputs += [seg_qT, seg_kvT]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale,
                          n_kv=n_kv, has_seg=has_seg),
        grid=(B, n_h, n_q, n_kv),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, hb, bq, D),
                               lambda b, h, i, j: (b, h, i, _zi())),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((hb, bq, D), jnp.float32)],
        interpret=interpret,
    )(*dq_inputs)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale,
                          n_q=n_q, has_seg=has_seg),
        grid=(B, n_h, n_kv, n_q),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, hb, bk, D), lambda b, h, j, i: (b, h, j, _zi())),
            pl.BlockSpec((1, hb, bk, D), lambda b, h, j, i: (b, h, j, _zi())),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((hb, bk, D), jnp.float32),
            pltpu.VMEM((hb, bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_inputs)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def flash_attention(q, k, v, seg_q=None, seg_kv=None, causal=False,
                    sm_scale=1.0, block_q=512, block_k=512, block_h=0,
                    interpret=False):
    """Blockwise (flash) attention: softmax(scale * Q K^T + mask) V.

    q, k, v: (B, H, L, D); seg_q/seg_kv: (B, L) int32 segment ids (None =
    no masking); positions attend only within equal segment ids.  Returns
    (B, H, Lq, D) in q's dtype.  ``block_h=0`` auto-picks the head-block
    (largest divisor of H under the VMEM budget).  ``interpret=True`` runs
    the Pallas interpreter (CPU tests).

    Numeric contract: the running max is clamped at -1e4 (``_M_FLOOR``) so
    masked logits (-1e30) contribute an exact 0.0 without a second where
    pass.  Consequence: a row whose TRUE max logit is below -1e4 (only
    reachable with exploding/degenerate logits — |scale*q.k| >= 1e4)
    underflows entirely and returns zeros with zero grads instead of exact
    softmax.  Normal-scale inputs (|logits| < 1e4) are unaffected; rows
    that are fully MASKED also return zeros by design.
    """
    out, _ = _flash_fwd(q, k, v, seg_q, seg_kv, causal, sm_scale,
                        block_q, block_k, block_h, interpret)
    return out


def _canon_segs(q, k, seg_q, seg_kv):
    if seg_q is None and seg_kv is None:
        # STATIC no-mask specialization: the kernels compile without seg
        # inputs, mask construction, or where passes (pure causal or
        # full attention)
        return None, None
    if seg_q is None or seg_kv is None:
        # equality masking cannot express "one side all-valid" without
        # knowing the other side's ids; silently zero-filling would make
        # real-id queries match NOTHING (all-masked garbage)
        raise ValueError(
            "flash_attention: pass BOTH seg_q and seg_kv or neither "
            "(one-sided segment ids have no well-defined mask)")
    return seg_q.astype(jnp.int32), seg_kv.astype(jnp.int32)


def _flash_fwd(q, k, v, seg_q, seg_kv, causal, sm_scale, block_q, block_k,
               block_h, interpret):
    sq, skv = _canon_segs(q, k, seg_q, seg_kv)
    out, lse = _fwd(q, k, v, sq, skv, causal, float(sm_scale),
                    block_q, block_k, block_h, interpret)
    return out, (q, k, v, sq, skv, out, lse)


def _flash_fwd_rule(q, k, v, seg_q, seg_kv, causal, sm_scale, block_q,
                    block_k, block_h, interpret):
    out, res = _flash_fwd(q, k, v, seg_q, seg_kv, causal, sm_scale,
                          block_q, block_k, block_h, interpret)
    return out, res


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, block_h, interpret,
                    res, g):
    q, k, v, sq, skv, out, lse = res
    dq, dk, dv = _bwd(q, k, v, sq, skv, out, lse, g, causal,
                      float(sm_scale), block_q, block_k, block_h, interpret)
    return dq, dk, dv, None, None


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
