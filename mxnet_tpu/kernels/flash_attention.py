"""FlashAttention-2 for TPU in Pallas — forward + full custom backward.

Blockwise-softmax attention with O(L) memory: probabilities never
materialize in HBM (SURVEY §5.7; replaces the reference's full
softmax(QK^T) path in src/operator/contrib/transformer.cc).  Written
in-house rather than wrapping jax.experimental's kernel because this
framework runs with jax_enable_x64 on (MXNet float64 parity) and the
upstream kernel's index arithmetic miscompiles under x64 — everything
here pins explicit int32/float32 types, including BlockSpec index-map
literals (see ``_zi``).  This kernel is the TPU branch of
``contrib.masked_selfatt`` / ``contrib.masked_att_qkv``
(``ops/contrib.py::_attend``), gated by a one-time compile probe that
falls back to the dense fp32 path on toolchains that reject the IR.

Layout: q, k, v are (batch, heads, seq, head_dim); segment ids are
(batch, seq) int32 — attention only flows between positions with EQUAL
segment ids (padding mask: valid tokens segment 1, pad tokens 0).

Grid design (canonical TPU flash schedule): grid (B, H, n_q, n_kv) with the
kv dimension innermost — TPU grid steps run sequentially per core, so the
running (m, l, acc) live in VMEM scratch across kv steps and the output
block writes once on the last kv step.  All matmuls hit the MXU at
(block, block) granularity with float32 accumulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_NEG_INF = -1e30
_LANES = 128     # VPU lane width: per-row scalars are stored broadcast over lanes
_SUBLANES = 8    # min sublane count — kv segment ids ride a (8, bk) tile


def _zi():
    """int32 zero for BlockSpec index maps.  Under jax_enable_x64 (this
    framework's default, MXNet float64 parity) a literal ``0`` in an index
    map becomes an i64 constant that Mosaic fails to legalize
    ('func.return (i32, i32, i32, i64)'); an explicit int32 compiles."""
    return jnp.int32(0)


def _mask_block(sq_ref, skv_ref, causal, iq, ik, bq, bk):
    """(bq, bk) bool mask for one tile; int32 iota only (x64-safe).

    sq_ref block is (1, bq, LANES) (q ids broadcast over lanes), skv_ref is
    (1, SUBLANES, bk) (kv ids broadcast over sublanes) — the tile-legal
    layout trick for 1-per-row scalars."""
    sq = sq_ref[0][:, :1]          # (bq, 1)
    skv = skv_ref[0][:1, :]        # (1, bk)
    mask = sq == skv
    if causal:
        qi = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
        ki = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
        mask = jnp.logical_and(mask, qi >= ki)
    return mask


def _mask_block_T(sqT_ref, skvT_ref, causal, iq, ik, bq, bk):
    """(bk, bq) mask — the TRANSPOSED tile for the dk/dv kernel, built
    directly from transposed segment layouts (sqT (1, SUBLANES, bq) q ids
    over lanes, skvT (1, bk, LANES) kv ids over sublanes) because Mosaic
    cannot legalize a bool vector transpose (`tpu.transpose` on i1)."""
    sq = sqT_ref[0][:1, :]         # (1, bq)
    skv = skvT_ref[0][:, :1]       # (bk, 1)
    mask = skv == sq               # (bk, bq)
    if causal:
        ki = jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 0) + ik * bk
        qi = jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 1) + iq * bq
        mask = jnp.logical_and(mask, qi >= ki)
    return mask


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, sq_ref, skv_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, causal, scale, n_kv):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0, 0]                     # (bq, d)
    k = k_ref[0, 0]                     # (bk, d)
    v = v_ref[0, 0]
    bq, bk = q.shape[0], k.shape[0]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT) * jnp.float32(scale)          # (bq, bk)
    mask = _mask_block(sq_ref, skv_ref, causal, iq, ik, bq, bk)
    s = jnp.where(mask, s, jnp.float32(_NEG_INF))

    m_prev = m_scr[:, :1]                                     # (bq, 1)
    l_prev = l_scr[:, :1]
    m_cur = jnp.max(s, axis=1, keepdims=True)                 # (bq, 1)
    m_new = jnp.maximum(m_prev, m_cur)
    # rows with every position masked stay at -inf; exp would overflow NaN
    p = jnp.exp(s - m_new)                                    # (bq, bk) f32
    p = jnp.where(mask, p, jnp.float32(0.0))
    alpha = jnp.exp(m_prev - m_new)                           # (bq, 1)
    l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)

    acc = acc_scr[...] * alpha
    acc_scr[...] = acc + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == n_kv - 1)
    def _finish():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == jnp.float32(0.0), jnp.float32(1.0), l)                  # fully-masked rows
        o_ref[0, 0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        lse = m_scr[:, :1] + jnp.log(safe_l)                  # (bq, 1)
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _fwd(q, k, v, seg_q, seg_kv, causal, scale, block_q, block_k, interpret):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq, bk = min(block_q, Lq), min(block_k, Lk)
    n_q, n_kv = Lq // bq, Lk // bk
    grid = (B, H, n_q, n_kv)
    seg_q = jnp.broadcast_to(seg_q[:, :, None], (B, Lq, _LANES))
    seg_kv = jnp.broadcast_to(seg_kv[:, None, :], (B, _SUBLANES, Lk))

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, scale=scale,
                          n_kv=n_kv),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, _zi())),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, _zi())),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, _zi())),
            pl.BlockSpec((1, bq, _LANES), lambda b, h, i, j: (b, i, _zi())),
            pl.BlockSpec((1, _SUBLANES, bk), lambda b, h, i, j: (b, _zi(), j)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, _zi())),
            pl.BlockSpec((1, 1, bq, _LANES), lambda b, h, i, j: (b, h, i, _zi())),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Lq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, seg_q, seg_kv)
    return out, lse[..., 0]  # lse (B, H, Lq)


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               sq_ref, skv_ref, dq_ref, dq_scr, *, causal, scale, n_kv):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0].astype(jnp.float32)                     # (bq, d)
    lse = lse_ref[0, 0][:, :1]                                # (bq, 1)
    delta = delta_ref[0, 0][:, :1]                            # (bq, 1)
    bq, bk = q.shape[0], k.shape[0]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT) * jnp.float32(scale)
    mask = _mask_block(sq_ref, skv_ref, causal, iq, ik, bq, bk)
    p = jnp.where(mask, jnp.exp(s - lse), jnp.float32(0.0))                # (bq, bk)
    dp = jax.lax.dot_general(
        do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)                   # (bq, bk)
    ds = p * (dp - delta) * jnp.float32(scale)
    dq_scr[...] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)

    @pl.when(ik == n_kv - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                sqT_ref, skvT_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                *, causal, scale, n_q):
    ik = pl.program_id(2)   # kv block: outer
    iq = pl.program_id(3)   # q block: inner (sequential accumulation)

    @pl.when(iq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0]                                         # (bq, d)
    lse = lse_ref[0, 0][:, :1]                                # (bq, 1)
    delta = delta_ref[0, 0][:, :1]
    bq, bk = q.shape[0], k.shape[0]

    # transposed tile: sT (bk, bq)
    sT = jax.lax.dot_general(
        k, q, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT) * jnp.float32(scale)
    maskT = _mask_block_T(sqT_ref, skvT_ref, causal, iq, ik, bq, bk)
    pT = jnp.where(maskT, jnp.exp(sT - lse[:, 0][None, :]), jnp.float32(0.0))  # (bk, bq)
    dv_scr[...] += jax.lax.dot_general(
        pT.astype(do.dtype), do, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)
    dpT = jax.lax.dot_general(
        v, do, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)                   # (bk, bq)
    dsT = pT * (dpT - delta[:, 0][None, :]) * jnp.float32(scale)
    dk_scr[...] += jax.lax.dot_general(
        dsT.astype(q.dtype), q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)

    @pl.when(iq == n_q - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd(q, k, v, seg_q, seg_kv, out, lse, do, causal, scale,
         block_q, block_k, interpret):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq, bk = min(block_q, Lq), min(block_k, Lk)
    n_q, n_kv = Lq // bq, Lk // bk

    # delta_i = rowsum(dO * O): cheap elementwise reduce, XLA fuses it
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                                   # (B, H, Lq)
    lse_b = jnp.broadcast_to(lse[..., None], lse.shape + (_LANES,))
    delta_b = jnp.broadcast_to(delta[..., None], delta.shape + (_LANES,))
    # two layouts of each segment-id vector: per-sublane-row for the dq
    # kernel's (bq, bk) mask, per-lane for the dkv kernel's (bk, bq) mask
    seg_qr = jnp.broadcast_to(seg_q[:, :, None], (B, Lq, _LANES))
    seg_kvl = jnp.broadcast_to(seg_kv[:, None, :], (B, _SUBLANES, Lk))
    seg_qT = jnp.broadcast_to(seg_q[:, None, :], (B, _SUBLANES, Lq))
    seg_kvT = jnp.broadcast_to(seg_kv[:, :, None], (B, Lk, _LANES))

    row_spec = pl.BlockSpec((1, 1, bq, _LANES), lambda b, h, i, j: (b, h, i, _zi()))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale, n_kv=n_kv),
        grid=(B, H, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, _zi())),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, _zi())),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, _zi())),
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, _zi())),
            row_spec,
            row_spec,
            pl.BlockSpec((1, bq, _LANES), lambda b, h, i, j: (b, i, _zi())),
            pl.BlockSpec((1, _SUBLANES, bk), lambda b, h, i, j: (b, _zi(), j)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, _zi())),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b, seg_qr, seg_kvl)

    row_spec_T = pl.BlockSpec((1, 1, bq, _LANES),
                              lambda b, h, j, i: (b, h, i, _zi()))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale, n_q=n_q),
        grid=(B, H, n_kv, n_q),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, _zi())),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, _zi())),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, _zi())),
            pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, _zi())),
            row_spec_T,
            row_spec_T,
            pl.BlockSpec((1, _SUBLANES, bq), lambda b, h, j, i: (b, _zi(), i)),
            pl.BlockSpec((1, bk, _LANES), lambda b, h, j, i: (b, j, _zi())),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, _zi())),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, _zi())),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b, seg_qT, seg_kvT)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_attention(q, k, v, seg_q=None, seg_kv=None, causal=False,
                    sm_scale=1.0, block_q=128, block_k=128,
                    interpret=False):
    """Blockwise (flash) attention: softmax(scale * Q K^T + mask) V.

    q, k, v: (B, H, L, D); seg_q/seg_kv: (B, L) int32 segment ids (None =
    no masking); positions attend only within equal segment ids.  Returns
    (B, H, Lq, D) in q's dtype.  ``interpret=True`` runs the Pallas
    interpreter (CPU tests).
    """
    out, _ = _flash_fwd(q, k, v, seg_q, seg_kv, causal, sm_scale,
                        block_q, block_k, interpret)
    return out


def _canon_segs(q, k, seg_q, seg_kv):
    B, _, Lq, _ = q.shape
    Lk = k.shape[2]
    if seg_q is None:
        seg_q = jnp.zeros((B, Lq), jnp.int32)
        seg_kv = jnp.zeros((B, Lk), jnp.int32)
    return seg_q.astype(jnp.int32), seg_kv.astype(jnp.int32)


def _flash_fwd(q, k, v, seg_q, seg_kv, causal, sm_scale, block_q, block_k,
               interpret):
    sq, skv = _canon_segs(q, k, seg_q, seg_kv)
    out, lse = _fwd(q, k, v, sq, skv, causal, float(sm_scale),
                    block_q, block_k, interpret)
    return out, (q, k, v, sq, skv, out, lse)


def _flash_fwd_rule(q, k, v, seg_q, seg_kv, causal, sm_scale, block_q,
                    block_k, interpret):
    out, res = _flash_fwd(q, k, v, seg_q, seg_kv, causal, sm_scale,
                          block_q, block_k, interpret)
    return out, res


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, sq, skv, out, lse = res
    dq, dk, dv = _bwd(q, k, v, sq, skv, out, lse, g, causal,
                      float(sm_scale), block_q, block_k, interpret)
    return dq, dk, dv, None, None


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
