"""Ring attention — sequence/context parallelism over a mesh axis
(SURVEY §5.7: ABSENT upstream; first-class here per the blueprint).

Liu et al., "Ring Attention with Blockwise Transformers" (2023): shard the
sequence over a mesh axis; each device holds its own Q block and rotates
the K/V blocks around the ring (``jax.lax.ppermute`` — ICI
neighbor-to-neighbor traffic) while accumulating blockwise-softmax
partials online, so a sequence of length L costs O(L/n) memory per device
and the K/V transfer overlaps with the block matmuls.

Two layers:

 - ``ring_attention(q, k, v, axis_name, ...)`` — call INSIDE
   ``shard_map`` with q/k/v already sequence-sharded (B, H, L/n, D).
   Pure jnp blockwise math (score tiles are (L/n, L/n) — already the n²
   memory win) with a numerically-stable online combine; fully
   differentiable end to end (ppermute's transpose is the reverse
   rotation, so the backward pass rotates gradients the other way
   automatically — no hand-written ring backward needed).
 - ``sequence_parallel_attention(q, k, v, mesh, axis, ...)`` — takes
   GLOBAL arrays, builds the shard_map over ``mesh``'s ``axis`` and
   returns the globally-assembled output: the user-facing entry for
   gluon attention layers when a sequence-parallel mesh is active.

Causal masking uses the ring step to know each incoming block's global
position: kv block from device j attends fully when j < i, in-block
causally when j == i, not at all when j > i.

Output rows whose every key is masked (fully-padded positions) are
mathematically undefined; like the flash kernels, they return finite
garbage — mask them downstream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ring_attention", "sequence_parallel_attention"]

_NEG_INF = -1e30


def _block_attn(q, k, v, scale, mask):
    """One (Lq, Lk) tile → (normalized block output f32, block lse f32).

    Invariant used by the combine: ``out`` is the softmax-weighted value
    over THIS block's keys; ``lse = log sum_k exp(s_k)`` for the block.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32) / l
    lse = (m + jnp.log(l))[..., 0]
    return out, lse


def _merge(out_a, lse_a, out_b, lse_b):
    """Combine two normalized partials (out, lse) exactly."""
    m = jnp.maximum(lse_a, lse_b)
    ea = jnp.exp(lse_a - m)
    eb = jnp.exp(lse_b - m)
    denom = ea + eb
    out = (out_a * ea[..., None] + out_b * eb[..., None]) / denom[..., None]
    return out, m + jnp.log(denom)


def ring_attention(q, k, v, axis_name, seg_q=None, seg_kv=None,
                   causal=False, sm_scale=1.0):
    """Sequence-parallel attention INSIDE shard_map.

    q, k, v: (B, H, Lb, D) — this device's sequence block; seg_q/seg_kv:
    (B, Lb) int32 segment ids (padding mask; None = attend all).  Returns
    (B, H, Lb, D) in q's dtype.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, H, Lb, D = q.shape
    if seg_q is None:
        seg_q = jnp.zeros((B, Lb), jnp.int32)
    if seg_kv is None:
        # K's block length, not Q's (they differ if K/V ever carry a
        # different per-device sequence block than Q)
        seg_kv = jnp.zeros((k.shape[0], k.shape[2]), jnp.int32)
    perm = [(i, (i + 1) % n) for i in range(n)]  # rotate kv to the right

    acc = jnp.zeros((B, H, Lb, D), jnp.float32)
    lse = jnp.full((B, H, Lb), _NEG_INF, jnp.float32)
    kb, vb, sb = k, v, seg_kv
    for step in range(n):
        src = (idx - step) % n  # owner of the kv block this step
        seg_mask = seg_q[:, None, :, None] == sb[:, None, None, :]
        if causal:
            qpos = idx * Lb + jax.lax.broadcasted_iota(
                jnp.int32, (Lb, Lb), 0)
            kpos = src * Lb + jax.lax.broadcasted_iota(
                jnp.int32, (Lb, Lb), 1)
            mask = seg_mask & (qpos >= kpos)[None, None]
        else:
            mask = seg_mask
        bout, blse = _block_attn(q, kb, vb, sm_scale, mask)
        acc, lse = _merge(acc, lse, bout, blse)
        if step != n - 1:
            kb = jax.lax.ppermute(kb, axis_name, perm)
            vb = jax.lax.ppermute(vb, axis_name, perm)
            sb = jax.lax.ppermute(sb, axis_name, perm)
    return acc.astype(q.dtype)


def sequence_parallel_attention(q, k, v, mesh, axis="sp", seg_q=None,
                                seg_kv=None, causal=False, sm_scale=1.0):
    """GLOBAL (B, H, L, D) arrays → ring attention over ``mesh[axis]``.

    L must divide evenly over the axis size.  Builds (and caches per call
    site via jit) the shard_map; q/k/v shard on the sequence dim, batch
    and heads stay replicated across the axis (combine with dp/tp axes by
    nesting shard_maps or pjit shardings outside).
    """
    from jax.sharding import PartitionSpec as P
    from . import shard_map_compat
    shard_map = shard_map_compat()

    if hasattr(mesh, "mesh"):            # accept DeviceMesh too
        mesh = mesh.mesh
    n = mesh.shape[axis] if isinstance(mesh.shape, dict) else dict(
        zip(mesh.axis_names, mesh.devices.shape))[axis]
    L = q.shape[2]
    if L % n:
        raise ValueError(f"sequence length {L} must divide over "
                         f"{n} '{axis}' devices")

    spec_x = P(None, None, axis, None)
    spec_s = P(None, axis)
    has_seg = seg_q is not None or seg_kv is not None
    if has_seg:
        # one-sided segment masks are legal: the absent side defaults to
        # the kernel's all-zeros segment (matches kv/q ids of 0) — gating
        # on seg_q alone silently dropped a seg_kv-only padding mask
        if seg_q is None:
            seg_q = jnp.zeros((q.shape[0], q.shape[2]), jnp.int32)
        if seg_kv is None:
            # K's length, not Q's: the sides differ in cross-attention
            seg_kv = jnp.zeros((k.shape[0], k.shape[2]), jnp.int32)

    def local(qb, kb, vb, *segs):
        sq, skv = (segs if has_seg else (None, None))
        return ring_attention(qb, kb, vb, axis, seg_q=sq, seg_kv=skv,
                              causal=causal, sm_scale=sm_scale)

    in_specs = (spec_x, spec_x, spec_x) + ((spec_s, spec_s) if has_seg
                                           else ())
    fn = shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=spec_x)
    # reshard inputs onto the mesh first: when this runs EAGERLY (e.g. a
    # TrainStep tape-capture pass) the operands arrive committed to a
    # single device and shard_map would reject them; under a jit trace
    # device_put lowers to a sharding constraint instead
    import jax as _jax
    shx = _jax.sharding.NamedSharding(mesh, spec_x)
    shs = _jax.sharding.NamedSharding(mesh, spec_s)
    q, k, v = (_jax.device_put(x, shx) for x in (q, k, v))
    args = (q, k, v) + ((_jax.device_put(seg_q, shs),
                         _jax.device_put(seg_kv, shs)) if has_seg else ())
    return fn(*args)
