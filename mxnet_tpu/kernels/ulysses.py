"""Ulysses sequence parallelism — all-to-all head sharding
(SURVEY §5.7: ABSENT upstream; the alternative SP design to ring
attention, per DeepSpeed-Ulysses, Jacobs et al. 2023).

The trade: ring attention keeps the sequence sharded throughout and moves
K/V around the ring (n-1 neighbor hops); Ulysses does ONE all-to-all that
re-shards [sequence-parallel → head-parallel], runs completely LOCAL
dense/flash attention per head group, then all-to-alls back.  On TPU both
collectives ride ICI; Ulysses wins when heads ≥ mesh axis size and the
per-device sequence block is short (fewer, larger transfers; attention
itself needs no cross-device math), ring wins for very long sequences
where even L/n × L score tiles blow memory.

 - ``ulysses_attention(q, k, v, axis_name, ...)`` — call INSIDE shard_map
   with q/k/v sequence-sharded (B, H, L/n, D).  Internally:
   all_to_all(seq→heads) → local softmax(QKᵀ)V over the FULL sequence with
   H/n heads → all_to_all(heads→seq).  Fully differentiable (all_to_all
   transposes to the reverse all_to_all).
 - ``ulysses_sequence_parallel_attention(q, k, v, mesh, axis, ...)`` —
   user-facing: takes GLOBAL (B, H, L, D) arrays, shard_maps over the
   mesh axis, returns the global output.  Same signature/semantics as
   ``ring_attention.sequence_parallel_attention`` so layers can switch
   strategies by name.

Causal masking is straightforward here (unlike the ring): after the first
all-to-all every device sees the full sequence, so it's one lower-left
triangular mask on the local (L, L) scores.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ulysses_attention", "ulysses_sequence_parallel_attention"]

_NEG_INF = -1e30


def ulysses_attention(q, k, v, axis_name, causal=False, scale=1.0):
    """Inside-shard_map body: q/k/v (B, H, Lb, D) sequence-sharded blocks.

    Same convention as the ring kernel: ``scale`` defaults to 1.0
    (unscaled — the caller applies 1/√d).  The head dim H must divide by
    the axis size n (standard Ulysses requirement — heads are what gets
    scattered)."""
    # jax.lax.axis_size doesn't exist on this toolchain (jax 0.4.x);
    # psum over the literal 1 folds to the static axis size — the same
    # idiom ring_attention.py uses
    n = jax.lax.psum(1, axis_name)
    B, H, Lb, D = q.shape
    if H % n:
        raise ValueError(f"ulysses: heads {H} not divisible by axis {n}")

    def seq_to_heads(x):
        # (B, H, Lb, D) seq-sharded → (B, H/n, L, D) head-sharded: the
        # tiled all_to_all splits the head dim into n groups (device i
        # keeps group i) and concatenates the peers' seq blocks, in peer
        # order, along the L dim
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def heads_to_seq(x):
        # (B, H/n, L, D) head-sharded → (B, H, Lb, D): exact inverse
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    L = qh.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vh.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return heads_to_seq(out.astype(q.dtype))


# jit cache: a fresh closure per call would retrace+recompile every step
# (the same trap parallel.py's collective cache exists for)
_jit_cache: dict = {}


def ulysses_sequence_parallel_attention(q, k, v, mesh, axis="sp",
                                        seg_q=None, seg_kv=None,
                                        causal=False, sm_scale=1.0):
    """Global entry: q/k/v (B, H, L, D); shards L over ``axis`` and runs
    the all-to-all schedule.  Drop-in for the ring strategy's
    ``sequence_parallel_attention`` — SAME signature and defaults
    (``sm_scale=1.0`` i.e. unscaled, like the ring kernel: the caller
    applies 1/√d).  Segment masking is a ring-only feature for now."""
    from . import shard_map_compat
    if seg_q is not None or seg_kv is not None:
        raise NotImplementedError(
            "ulysses: segment masking not implemented — use the ring "
            "strategy (sequence_parallel_attention) for segmented batches")
    raw_mesh = mesh.mesh if hasattr(mesh, "mesh") else mesh
    # key by device ids + axes (the _collective_cache convention), not
    # object identity: rebuilding a DeviceMesh per phase must hit the
    # cache, and jax.jit already keys shapes itself
    key = (tuple(d.id for d in raw_mesh.devices.flat),
           tuple(raw_mesh.axis_names), tuple(raw_mesh.devices.shape),
           axis, causal, float(sm_scale))
    f = _jit_cache.get(key)
    if f is None:
        P = jax.sharding.PartitionSpec
        spec = P(None, None, axis, None)

        def body(qq, kk, vv):
            return ulysses_attention(qq, kk, vv, axis, causal=causal,
                                     scale=sm_scale)

        f = jax.jit(shard_map_compat()(
            body, mesh=raw_mesh, in_specs=(spec, spec, spec),
            out_specs=spec))
        _jit_cache[key] = f
    # reshard first: eager callers (TrainStep tape capture) hand over
    # single-device-committed arrays the shard_map would reject; under a
    # jit trace this is just a sharding constraint
    sh = jax.sharding.NamedSharding(
        raw_mesh, jax.sharding.PartitionSpec(None, None, axis, None))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    return f(q, k, v)
