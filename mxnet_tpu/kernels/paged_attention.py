"""Paged-KV attention — the serving engine's decode-step attention core.

vLLM's PagedAttention idea (SOSP'23), shaped for the fixed-shape/no-retrace
discipline of this rebuild: the KV cache lives in a pool of fixed-size
*blocks* of ``block_tokens`` positions each, and every sequence owns a
*block table* — a row of pool indices mapping its logical positions to
physical blocks.  Sequences of wildly different lengths then share one
preallocated pool at ONE compiled shape: growing a sequence allocates a
block (a host-side free-list pop), finishing one returns its blocks, and
the compiled executable never changes because every operand — pool, block
tables, context lengths — keeps its shape across iterations.

This module is the dense (XLA-native) implementation: block gathers via
``pool[table]`` and a masked fp32 softmax, which XLA fuses well at serving
batch sizes and runs on every backend (CPU tests included).  It is written
to the same shape contract as the Pallas TPU paged kernel family
(jax.experimental paged_attention: per-page DMA + online softmax), so a
Mosaic kernel can slot in behind the same signature later without touching
the serving engine.  The numerics deliberately mirror
``ops.contrib._dense_sdpa`` — scores einsum in the input dtype, cast to
f32, ``-1e9`` masking, fp32 softmax, cast back — so incremental decode is
token-identical to the full re-encode forward it replaces.

Shape glossary (one layer):
    k_pool, v_pool : (num_blocks, block_tokens, kv_heads, head_dim)
    block_table    : (B, max_blocks) int32 — pool indices per sequence
    ctx_len        : (B,) int32 — positions readable (current included)
    q              : (B, heads, q_len, head_dim)

Block 0 of every pool is the SCRATCH block: inactive batch slots point
their whole table at it, so their (discarded) writes land somewhere
harmless and freed blocks can be re-issued immediately with no zeroing —
a reused block is only ever read at positions < ctx_len, every one of
which the new owner has overwritten.
"""

from __future__ import annotations

__all__ = ["paged_attention", "paged_attention_multi", "write_kv",
           "write_kv_multi", "write_kv_prefill", "SCRATCH_BLOCK"]

# pool index reserved for discarded writes (inactive slots, pad positions)
SCRATCH_BLOCK = 0


def _jnp():
    import jax.numpy as jnp
    return jnp


def _paged_gather_attend(q, k_pool, v_pool, block_table, readable,
                         num_kv_groups, sm_scale):
    """Shared gather + masked-softmax core: ``readable`` is the (B, Lq)
    per-query count of readable pool positions (same numerics discipline
    as ``_dense_sdpa``: scores einsum in the input dtype, f32 softmax,
    ``-1e9`` masking)."""
    import jax
    jnp = _jnp()
    B, H, Lq, D = q.shape
    _, T, KV, _ = k_pool.shape
    MB = block_table.shape[1]
    S = MB * T
    # gather: (B, MB, T, KV, D) -> (B, KV, S, D) head-major like _attend
    k = jnp.transpose(k_pool[block_table].reshape(B, S, KV, D), (0, 2, 1, 3))
    v = jnp.transpose(v_pool[block_table].reshape(B, S, KV, D), (0, 2, 1, 3))
    if num_kv_groups > 1:
        k = jnp.repeat(k, num_kv_groups, axis=1)
        v = jnp.repeat(v, num_kv_groups, axis=1)
    scale = sm_scale if sm_scale is not None else 1.0 / float(D) ** 0.5
    att = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    pos = jnp.arange(S, dtype=jnp.int32)
    mask = pos[None, None, None, :] < readable[:, None, :, None]
    att = jnp.where(mask, att, jnp.asarray(-1e9, jnp.float32))
    p = jax.nn.softmax(att, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def paged_attention(q, k_pool, v_pool, block_table, ctx_len,
                    num_kv_groups=1, sm_scale=None):
    """Attention of ``q`` against the paged K/V of each sequence.

    ``q`` is (B, H, Lq, D) — Lq is 1 on the decode path; ``ctx_len`` (B,)
    counts readable positions (the caller writes the current token's k/v
    FIRST, so ctx_len includes it).  GQA rides ``num_kv_groups`` = H /
    kv_heads with the same head-major broadcast as
    ``contrib.masked_att_qkv``.  Returns (B, H, Lq, D).
    """
    jnp = _jnp()
    readable = jnp.broadcast_to(ctx_len[:, None],
                                (q.shape[0], q.shape[2]))
    return _paged_gather_attend(q, k_pool, v_pool, block_table, readable,
                                num_kv_groups, sm_scale)


def paged_attention_multi(q, k_pool, v_pool, block_table, pos0,
                          num_kv_groups=1, sm_scale=None):
    """Multi-query paged attention: query j of sequence b sits at
    absolute position ``pos0[b] + j`` and attends every pool position
    <= its own (causal within the chunk, full paged history before it).

    ``q`` is (B, H, K, D) — the K-token speculative-verify / tail-prefill
    chunk; the caller scatters the chunk's K/V FIRST (``write_kv_multi``)
    so query j reads chunk keys 0..j through the pool like the 1-token
    decode path reads its own freshly-written position.
    """
    jnp = _jnp()
    K = q.shape[2]
    readable = pos0[:, None] + jnp.arange(1, K + 1, dtype=pos0.dtype)[None]
    return _paged_gather_attend(q, k_pool, v_pool, block_table, readable,
                                num_kv_groups, sm_scale)


def write_kv(k_pool, v_pool, block_table, pos, k_new, v_new, valid=None):
    """Scatter one token's k/v per sequence into its block-table slot.

    ``pos`` (B,) is the logical position being written (== ctx_len before
    the write); ``k_new``/``v_new`` are (B, KV, D).  Returns the updated
    pools.  Slots the scheduler parked on the scratch table all collide at
    block 0 — by design, those writes are never read back.  ``valid``
    (B,) bool, when given, routes invalid rows' writes to the scratch
    block instead — the draft model's over-the-budget speculation steps
    must not scribble past a slot's reserved blocks.
    """
    jnp = _jnp()
    N, T, KV, D = k_pool.shape
    MB = block_table.shape[1]
    bi = pos // T
    blk = jnp.take_along_axis(block_table, jnp.minimum(bi, MB - 1)[:, None],
                              axis=1)[:, 0]
    idx = blk * T + pos % T                                   # (B,) flat
    if valid is not None:
        ok = valid & (bi < MB)
        idx = jnp.where(ok, idx, SCRATCH_BLOCK * T + pos % T)
    k_pool = k_pool.reshape(N * T, KV, D).at[idx].set(k_new).reshape(
        N, T, KV, D)
    v_pool = v_pool.reshape(N * T, KV, D).at[idx].set(v_new).reshape(
        N, T, KV, D)
    return k_pool, v_pool


def write_kv_multi(k_pool, v_pool, block_table, pos0, n_valid,
                   k_new, v_new):
    """Scatter a K-token chunk's k/v per sequence (speculative verify /
    prefix-cache tail prefill).

    ``k_new``/``v_new`` are (B, K, KV, D) for positions ``pos0[b] + j``;
    chunk columns ``j >= n_valid[b]`` (beyond the slot's remaining token
    budget) and positions past the block table are routed to the scratch
    block — written, never read, exactly like padded prefill positions.
    Returns the updated pools.
    """
    jnp = _jnp()
    N, T, KV, D = k_pool.shape
    MB = block_table.shape[1]
    B, K = k_new.shape[0], k_new.shape[1]
    pos = pos0[:, None] + jnp.arange(K, dtype=pos0.dtype)[None]   # (B, K)
    bi = pos // T
    blk = jnp.take_along_axis(block_table, jnp.minimum(bi, MB - 1), axis=1)
    ok = (jnp.arange(K, dtype=jnp.int32)[None] < n_valid[:, None]) \
        & (bi < MB)
    idx = jnp.where(ok, blk * T + pos % T, SCRATCH_BLOCK * T + pos % T)
    idx = idx.reshape(B * K)
    k_pool = k_pool.reshape(N * T, KV, D).at[idx].set(
        k_new.reshape(B * K, KV, D)).reshape(N, T, KV, D)
    v_pool = v_pool.reshape(N * T, KV, D).at[idx].set(
        v_new.reshape(B * K, KV, D)).reshape(N, T, KV, D)
    return k_pool, v_pool


def write_kv_prefill(k_pool, v_pool, block_table_row, valid_len,
                     k_new, v_new):
    """Scatter a whole (padded) prompt's k/v into one sequence's blocks.

    ``k_new``/``v_new`` are (P, KV, D) for positions 0..P-1 of ONE
    sequence; ``block_table_row`` is its (max_blocks,) table;
    positions >= ``valid_len`` (padding) are routed to the scratch block
    instead, so the pad tail of the fixed prefill shape never touches a
    real block.  Returns the updated pools.
    """
    jnp = _jnp()
    N, T, KV, D = k_pool.shape
    P = k_new.shape[0]
    pos = jnp.arange(P, dtype=jnp.int32)
    blk = block_table_row[pos // T]                           # (P,)
    idx = blk * T + pos % T
    idx = jnp.where(pos < valid_len, idx, SCRATCH_BLOCK * T + pos % T)
    k_pool = k_pool.reshape(N * T, KV, D).at[idx].set(k_new).reshape(
        N, T, KV, D)
    v_pool = v_pool.reshape(N * T, KV, D).at[idx].set(v_new).reshape(
        N, T, KV, D)
    return k_pool, v_pool
