"""Hand-written Pallas TPU kernels.

The compute hot-spots the XLA autofuser can't schedule optimally get
explicit MXU/VMEM kernels here (SURVEY §5.7 long-context requirement; the
reference's analog is the hand-tuned CUDA in src/operator/contrib/
transformer.cu and mshadow).  Kernels are platform-gated by callers via
``jax.lax.platform_dependent`` — every kernel ships with a portable dense
fallback and an interpret-mode path used by the CPU test suite as the
numerics oracle.
"""

from .flash_attention import flash_attention  # noqa: F401
from .ring_attention import (ring_attention,  # noqa: F401
                             sequence_parallel_attention)
