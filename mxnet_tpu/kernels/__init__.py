"""Hand-written Pallas TPU kernels.

The compute hot-spots the XLA autofuser can't schedule optimally get
explicit MXU/VMEM kernels here (SURVEY §5.7 long-context requirement; the
reference's analog is the hand-tuned CUDA in src/operator/contrib/
transformer.cu and mshadow).  Kernels are platform-gated by callers via
``jax.lax.platform_dependent`` — every kernel ships with a portable dense
fallback and an interpret-mode path used by the CPU test suite as the
numerics oracle.
"""

import functools as _functools


def shard_map_compat():
    """The shard_map version shim, defined ONCE: new jax spells the
    replication check ``check_vma``, the experimental fallback spells it
    ``check_rep`` — callers get a shard_map with the check disabled
    either way (used by pipeline.py and the SP kernels)."""
    try:
        from jax import shard_map as _sm
        return _functools.partial(_sm, check_vma=False)
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        return _functools.partial(_sm, check_rep=False)


from .flash_attention import flash_attention  # noqa: F401,E402
from .ring_attention import (ring_attention,  # noqa: F401,E402
                             sequence_parallel_attention)
