"""gluon.Parameter / ParameterDict.

Rebuild of python/mxnet/gluon/parameter.py (P6): deferred allocation (shapes
with unknown dims resolved at first forward), per-context data, grad_req,
lr_mult/wd_mult, save/load.  TPU-native deltas:
 - one canonical buffer per Parameter (an NDArray over a jax.Array) instead of
   per-GPU copies; single-process multi-device data parallelism replicates /
   shards that one buffer via jax.sharding (see mxnet_tpu.parallel), so
   ``_reduce`` of per-ctx grads becomes an XLA collective, not a host loop.
 - an optional ``sharding`` hint (a PartitionSpec-like tuple) consumed by the
   parallel trainer for TP/FSDP layouts.
"""

from __future__ import annotations

import re

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray


class DeferredInitializationError(MXNetError):
    pass


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype=_np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default",
                 sharding=None):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self.stype = stype
        self.grad_stype = grad_stype
        self.sharding = sharding  # TPU: PartitionSpec axes hint for pjit
        # set by mxnet_tpu.sharding when a mesh computation (TrainStep)
        # already reduces this param's gradient: Trainer then skips the
        # (double-counting) local kvstore allreduce for it
        self.mesh_reduced = False
        self._data = None         # canonical buffer (ctx_list[0] replica)
        self._data_list = None    # one replica per ctx (multi-device DP)
        self._grad = None
        self._ctx_list = None
        self._deferred_init = None
        self._trainer = None

    # -- state ---------------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                for d in (self._data_list or [self._data]):
                    d.grad_req = "null"
                    d._grad = None
                self._grad = None
            else:
                self._init_grad()

    def _shape_complete(self):
        return (self.shape is not None and len(self.shape) > 0
                and all(s > 0 for s in self.shape))

    # -- initialization ------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        from .. import initializer as _initmod
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if not isinstance(ctx, (list, tuple)):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        init = init if init is not None else self.init
        if default_init is None:
            default_init = _initmod.Uniform()
        if not self._shape_complete():
            if not self.allow_deferred_init:
                raise MXNetError(
                    f"Cannot initialize Parameter {self.name!r}: shape "
                    f"{self.shape} is incomplete and deferred init is off")
            self._deferred_init = (init, default_init)
            return
        self._finish_init(init, default_init)

    def _finish_init(self, init, default_init):
        from .. import initializer as _initmod
        data = nd.zeros(self.shape, dtype=self.dtype, ctx=self._ctx_list[0])
        initializer = init if init is not None else default_init
        if isinstance(initializer, str):
            initializer = _initmod.get(initializer)
        desc = _initmod.InitDesc(self.name)
        initializer(desc, data)
        self._data = data
        # one replica per context: the reference's per-GPU copies
        # (gluon/parameter.py :: Parameter._init_impl broadcasts to ctx list)
        self._data_list = [data] + [data.copyto(c) for c in self._ctx_list[1:]]
        self._deferred_init = None
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        for d in (self._data_list or [self._data]):
            d.attach_grad(grad_req=self._grad_req)
        self._grad = self._data._grad

    def _finish_deferred_init(self, in_shape=None):
        """Called by layers at first forward once input shape is known."""
        if self._deferred_init is None:
            return
        if in_shape is not None:
            self.shape = tuple(in_shape)
        if not self._shape_complete():
            raise DeferredInitializationError(
                f"Parameter {self.name!r} deferred init could not infer a "
                f"complete shape (got {self.shape})")
        init, default_init = self._deferred_init
        self._finish_init(init, default_init)

    def shape_mismatch_update(self, new_shape):
        """Merge inferred dims into a partially-known shape."""
        if self.shape is None:
            self.shape = tuple(new_shape)
            return
        merged = []
        for old, new in zip(self.shape, new_shape):
            if old in (0, -1, None):
                merged.append(new)
            elif new in (0, -1, None) or old == new:
                merged.append(old)
            else:
                raise MXNetError(
                    f"Parameter {self.name!r}: inferred shape {new_shape} "
                    f"incompatible with declared {self.shape}")
        self.shape = tuple(merged)

    # -- access --------------------------------------------------------------
    def _check_initialized(self):
        if self._data is not None:
            return
        if self._deferred_init is not None:
            raise DeferredInitializationError(
                f"Parameter {self.name!r} has deferred initialization pending "
                "— run a forward pass first or set the input shape")
        raise MXNetError(
            f"Parameter {self.name!r} has not been initialized. Call "
            ".initialize() first")

    def _replica(self, ctx):
        """The replica living on ``ctx`` (reference raises when a parameter
        was not initialized on the requested context)."""
        if ctx is None or len(self._data_list) == 1:
            return self._data_list[0]
        # inside a jit trace (CachedOp / parallel.TrainStep) inputs are
        # tracers with no device, so ctx is the current-context fallback —
        # the canonical slot is the one bound to the traced value
        from .. import random as _rnd
        if _rnd.in_trace():
            return self._data_list[0]
        for c, d in zip(self._ctx_list, self._data_list):
            if c == ctx:
                return d
        raise MXNetError(
            f"Parameter {self.name!r} was not initialized on context {ctx}; "
            f"it lives on {self._ctx_list}")

    def data(self, ctx=None):
        self._check_initialized()
        return self._replica(ctx)

    def list_data(self):
        self._check_initialized()
        return list(self._data_list)

    def grad(self, ctx=None, stype=None):
        """Gradient buffer; with ``grad_stype='row_sparse'`` (e.g.
        Embedding(sparse_grad=True)) the result is a RowSparseNDArray
        holding only the touched rows.  TPU-native statement of the
        reference's sparse-grad path (src/operator/tensor/indexing_op.cc
        row_sparse Embedding backward): on device the gradient IS a fused
        XLA scatter-add into the dense buffer — already the sparse
        accumulation — and this view compresses it to (indices, values)
        for kvstore push / lazy optimizer updates."""
        self._check_initialized()
        g = self._replica(ctx)._grad
        if g is None:
            raise MXNetError(f"Parameter {self.name!r} has grad_req='null'")
        stype = stype or self.grad_stype
        if stype == "row_sparse":
            from ..ndarray import sparse as _sp
            return _sp.row_sparse_array(g)
        return g

    def list_grad(self):
        self._check_initialized()
        if self._data._grad is None:
            raise MXNetError(f"Parameter {self.name!r} has grad_req='null'")
        return [d._grad for d in self._data_list]

    def list_ctx(self):
        return list(self._ctx_list or [])

    def set_data(self, data):
        if self._data is None:
            # loading into an uninitialized/deferred parameter allocates it
            # directly from the data (reference load_parameters semantics)
            self.shape = tuple(data.shape)
            if self._ctx_list is None:
                self._ctx_list = [current_context()]
            src = data if isinstance(data, NDArray) else nd.array(data)
            self._data = NDArray._from_data(
                src.astype(self.dtype)._data, ctx=self._ctx_list[0])
            self._data_list = [self._data] \
                + [self._data.copyto(c) for c in self._ctx_list[1:]]
            self._deferred_init = None
            if self._grad_req != "null":
                self._init_grad()
            return
        self._check_initialized()
        if not isinstance(data, NDArray):
            data = nd.array(data, dtype=self.dtype, ctx=self._ctx_list[0])
        arr = data.astype(self.dtype)._data
        for d, c in zip(self._data_list, self._ctx_list):
            import jax
            d._set_data(jax.device_put(arr, c.jax_device()))

    def _reduce(self):
        """Sum per-ctx grads into one NDArray (reference Parameter._reduce)."""
        grads = self.list_grad()
        out = grads[0].copy()
        for g in grads[1:]:
            out += g.as_in_context(out.ctx)
        return out

    def zero_grad(self):
        if self._data_list is None:
            return
        for d in self._data_list:
            if d._grad is not None:
                d._grad._set_data(
                    nd.zeros(self.shape, dtype=self.dtype, ctx=d.ctx)._data)

    def reset_ctx(self, ctx):
        if not isinstance(ctx, (list, tuple)):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if self._data is not None:
            self._data = self._data.as_in_context(ctx[0])
            self._data_list = [self._data] \
                + [self._data.copyto(c) for c in ctx[1:]]
            if self._grad_req != "null":
                self._init_grad()

    def cast(self, dtype):
        self.dtype = _np.dtype(dtype)
        if self._data is not None:
            self._data = self._data.astype(dtype)
            self._data_list = [self._data] \
                + [self._data.copyto(c) for c in self._ctx_list[1:]]
            if self._grad_req != "null":
                self._init_grad()

    def var(self):
        from .. import symbol as sym
        return sym.var(self.name, shape=self.shape, dtype=self.dtype)

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"


class Constant(Parameter):
    """A non-trainable parameter holding a fixed value (reference
    gluon/parameter.py :: Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(value)
        self.value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype,
                         init="__constant__")

    def _finish_init(self, init, default_init):  # noqa: ARG002
        ctxs = self._ctx_list or [current_context()]
        self._data = self.value.copyto(ctxs[0])
        self._data_list = [self._data] \
            + [self._data.copyto(c) for c in ctxs[1:]]
        self._deferred_init = None


class ParameterDict:
    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __iter__(self):
        return iter(self._params.values())

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __contains__(self, name):
        return name in self._params

    def __getitem__(self, name):
        return self._params[name]

    def get(self, name, **kwargs):
        """Create-or-retrieve (reference semantics incl. shared lookup)."""
        full = self._prefix + name
        if full in self._params:
            p = self._params[full]
            for k, v in kwargs.items():
                if v is not None and getattr(p, k, None) in (None, 0, ()):
                    setattr(p, k, v)
            return p
        if self._shared is not None and full in self._shared:
            self._params[full] = self._shared[full]
            return self._params[full]
        p = Parameter(full, **kwargs)
        self._params[full] = p
        return p

    def get_constant(self, name, value=None):
        full = self._prefix + name
        if full not in self._params:
            self._params[full] = Constant(full, value)
        return self._params[full]

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):  # noqa: ARG002
        for p in self.values():
            p.initialize(init=init, ctx=ctx, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def select(self, pattern):
        """Regex-select a subset (reference collect_params('.*weight'))."""
        pat = re.compile(pattern)
        out = ParameterDict(self._prefix)
        for k, v in self.items():
            if pat.match(k):
                out._params[k] = v
        return out

    def save(self, filename, strip_prefix=""):
        arg = {}
        for k, p in self.items():
            key = k[len(strip_prefix):] if k.startswith(strip_prefix) else k
            arg[key] = p.data()
        nd.save(filename, arg)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        loaded = nd.load(filename, ctx=ctx)
        if restore_prefix:
            loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for k, p in self.items():
            if k in loaded:
                p.set_data(loaded[k])
            elif not allow_missing:
                raise MXNetError(f"Parameter {k} missing in file {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(self.keys())
            if extra:
                raise MXNetError(
                    f"File {filename} contains extra parameters: {sorted(extra)}")

    def __repr__(self):
        lines = "\n".join(f"  {v}" for v in self.values())
        return f"ParameterDict (\n{lines}\n)"
