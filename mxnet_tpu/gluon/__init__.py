"""gluon — the imperative/hybrid model API (reference python/mxnet/gluon/)."""

from .parameter import Parameter, ParameterDict, Constant  # noqa: F401
from .block import Block, HybridBlock, SymbolBlock, CachedOp  # noqa: F401
from .trainer import Trainer  # noqa: F401
from . import nn  # noqa: F401
from . import loss  # noqa: F401
from . import data  # noqa: F401
from . import utils  # noqa: F401


def __getattr__(name):
    import importlib
    if name in ("rnn", "model_zoo", "contrib"):
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu.gluon' has no attribute {name!r}")
