"""gluon.utils (reference python/mxnet/gluon/utils.py): split_and_load,
clip_global_norm, download (gated — no egress in this environment), check_sha1."""

from __future__ import annotations

import os

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so the joint L2 norm ≤ max_norm (returns the norm)."""
    import jax.numpy as jnp
    import math
    total = None
    for a in arrays:
        s = jnp.sum(jnp.square(a._data))
        total = s if total is None else total + s
    norm = float(jnp.sqrt(total))
    if check_isfinite and not math.isfinite(norm):
        import warnings
        warnings.warn("nan or inf found in clip_global_norm", stacklevel=2)
    scale = max_norm / (norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._set_data(a._data * scale)
    return norm


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):  # noqa: ARG001
    """Reference API; this environment has no network egress, so only a
    local cache hit can succeed."""
    fname = path if path and not os.path.isdir(path) else \
        os.path.join(path or ".", url.split("/")[-1])
    if os.path.exists(fname) and not overwrite and \
            (not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    raise MXNetError(
        f"cannot download {url}: network egress is unavailable in this "
        f"environment and {fname} is not cached locally")
