"""gluon.utils (reference python/mxnet/gluon/utils.py): split_and_load,
clip_global_norm, download (gated — no egress in this environment), check_sha1."""

from __future__ import annotations

import os

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download", "remat_call"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so the joint L2 norm ≤ max_norm (returns the norm)."""
    import jax.numpy as jnp
    import math
    total = None
    for a in arrays:
        s = jnp.sum(jnp.square(a._data))
        total = s if total is None else total + s
    norm = float(jnp.sqrt(total))
    if check_isfinite and not math.isfinite(norm):
        import warnings
        warnings.warn("nan or inf found in clip_global_norm", stacklevel=2)
    scale = max_norm / (norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._set_data(a._data * scale)
    return norm


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):  # noqa: ARG001
    """Reference API; this environment has no network egress, so only a
    local cache hit can succeed."""
    fname = path if path and not os.path.isdir(path) else \
        os.path.join(path or ".", url.split("/")[-1])
    if os.path.exists(fname) and not overwrite and \
            (not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    raise MXNetError(
        f"cannot download {url}: network egress is unavailable in this "
        f"environment and {fname} is not cached locally")


def remat_call(block, *inputs):
    """Run ``block(*inputs)`` with activation REMATERIALIZATION: the
    block's internal activations are not stored for backward — they are
    recomputed from the block inputs during the gradient pass
    (``jax.checkpoint``).  This is the TPU-native analog of the
    reference's ``MXNET_BACKWARD_DO_MIRROR`` memory/compute trade
    (docs/faq/env_var.md): backward does ~1 extra forward of compute and
    activation memory drops from O(layers) to O(1) per wrapped segment —
    what makes long-sequence configs fit one chip (SURVEY §5.7).

    The whole block becomes ONE node on the autograd tape (its vjp is the
    checkpointed function's vjp), so it composes with ``autograd.record``
    / ``TrainStep`` like any fused op.  Blocks that MUTATE state in
    forward (BatchNorm running stats) are rejected — the mutation would
    silently vanish.
    """
    import jax
    from .. import autograd
    from ..ndarray.ndarray import swap_slot_values

    params = [p for _, p in sorted(block.collect_params().items())]
    in_ctx = next((a.ctx for a in inputs if isinstance(a, NDArray)), None)
    param_nds = [p.data(in_ctx) for p in params]
    arrays = [a._data for a in inputs] + [p._data for p in param_nds]
    n_in = len(inputs)
    train = autograd.is_training()
    mutated = [False]

    @jax.checkpoint
    def f(*arrs):
        in_arr, p_arr = arrs[:n_in], arrs[n_in:]
        with swap_slot_values(zip(param_nds, p_arr)) as saved:
            in_nds = [NDArray._from_data(a) for a in in_arr]
            with autograd._scope(recording=False, training=train):
                out = block(*in_nds)
            if any(slot.value is not old and slot.value is not rep
                   for (slot, old), rep in zip(saved, p_arr)):
                mutated[0] = True
            if isinstance(out, (list, tuple)):
                raise MXNetError(
                    "remat_call supports single-output blocks")
            return out._data

    if autograd.is_recording():
        out_raw, vjp_fn = jax.vjp(f, *arrays)
    else:
        out_raw, vjp_fn = f(*arrays), None
    if mutated[0]:
        raise MXNetError(
            "remat_call: block mutates state in forward (BatchNorm "
            "running stats?) — rematerialization would re-run and then "
            "DROP the mutation; wrap only pure blocks")
    result = NDArray._from_data(out_raw, ctx=in_ctx)
    if vjp_fn is not None:
        # tape node with op=None (like autograd.Function): create_graph
        # backward then replays the stored vjp closure instead of trying
        # to re-dispatch a registry op that does not exist
        all_ins = list(inputs) + param_nds
        node = autograd._Node(
            "_remat_block", vjp_fn, autograd._entries_for(all_ins),
            [(result.shape, result.dtype)])
        autograd._st().tape.append(node)
        result._node = (node, 0)
    return result
