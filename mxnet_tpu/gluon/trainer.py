"""gluon.Trainer (reference python/mxnet/gluon/trainer.py, P6).

API parity: Trainer(params, optimizer, optimizer_params, kvstore,
update_on_kvstore), ``step(batch_size)``, ``allreduce_grads()``, ``update()``,
``save_states/load_states``, ``learning_rate`` property.

Multi-device data parallelism (reference flow, src/kvstore/comm.h ::
CommDevice::ReduceSum): parameters initialized on a ctx *list* carry one
replica per ctx; ``step`` pushes the per-ctx gradient list to the kvstore,
which sums it (one XLA add chain — ICI collectives when replicas live on
different TPU chips), pulls the reduced gradient back into every replica, and
runs one updater per ctx so replicas stay bit-identical.

``update_on_kvstore=True`` moves the optimizer into the store (the reference
runs it on the PS server; here the store applies it to its canonical copy and
``pull`` broadcasts updated weights).  The fused SPMD alternative — whole
train step jitted over a mesh — is mxnet_tpu.parallel.TrainStep.

.. note:: **Documented divergence from the reference.** Upstream Trainer
   defaults ``update_on_kvstore=True`` for ``local``/``device`` kvstores;
   here it defaults to **False** (optimizer state stays on device, the best
   placement on TPU where no server role exists).  Numerics are identical;
   what differs is where ``save_states`` finds optimizer state and that
   ``allreduce_grads()``/``update()`` are callable (they raise upstream when
   the kvstore owns the update).  Pass ``update_on_kvstore=True`` explicitly
   for reference-identical behavior.
"""

from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt
from .. import telemetry as _tel
from ..telemetry import stepclock as _sclock
from ..telemetry import tracer as _ttrace
from ..resilience import chaos as _chaos
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]

_M_STEPS = _tel.counter(
    "mxnet_trainer_steps_total", "Optimizer steps taken by gluon.Trainer.")
_M_STEP_SECONDS = _tel.histogram(
    "mxnet_trainer_step_seconds", "End-to-end Trainer.step latency.")


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("first argument must be a list/dict of Parameters")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p}")
            self._param2idx[p.name] = i
            self._params.append(p)
            p._trainer = self
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._compression_params = compression_params
        # reference defaults update_on_kvstore by kvstore type; on TPU the
        # optimizer is best on device (documented divergence for dist: no
        # server role exists), so default False unless explicitly requested
        self._update_on_kvstore = bool(update_on_kvstore)
        # flat reduced-gradient buckets handed from the kvstore's fused
        # allreduce straight to the fused optimizer (optimizer_fusion):
        # [(key_list, shapes, sizes, flat_array)] stashed per step
        self._flat_handoff = None

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params and set(optimizer_params) - {"rescale_grad"}:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        # one updater per device replica (reference Trainer._updaters): each
        # holds its own state copies so replicas update identically
        n_ctx = max((len(p.list_ctx()) or 1 for p in self._params), default=1)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in range(max(n_ctx, 1))]

    def _row_sparse_params(self):
        return [p for p in self._params if p.stype == "row_sparse"]

    def _init_kvstore(self):
        if self._kv_initialized:
            return
        # replicas may have been created after __init__ (deferred init):
        # make sure the updater list covers every ctx
        n_ctx = max((len(p.list_ctx()) or 1 for p in self._params), default=1)
        while len(self._updaters) < n_ctx:
            self._updaters.append(opt.get_updater(self._optimizer))
        kvt = self._kvstore_type
        if kvt is None or kvt is False:
            if self._update_on_kvstore:
                raise MXNetError(
                    "update_on_kvstore=True requires a kvstore "
                    "(reference raises for this combination)")
            self._kvstore = None
        elif isinstance(kvt, str):
            from .. import kvstore as kvs
            if kvt in ("local", "device", "nccl") and n_ctx <= 1 \
                    and not self._update_on_kvstore:
                self._kvstore = None  # single replica: reduction is identity
            else:
                self._kvstore = kvs.create(kvt)
        else:
            self._kvstore = kvt
        if self._kvstore is not None:
            if hasattr(self._kvstore, "_ensure_dist"):
                # surface distributed bring-up failures HERE, deadline-
                # bounded with a clear KVStoreTimeoutError (ISSUE 3),
                # instead of hanging inside the first step's collective
                self._kvstore._ensure_dist()
            if self._compression_params:
                self._kvstore.set_gradient_compression(
                    self._compression_params)
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    self._kvstore.init(i, p.data())
            if self._update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
        else:
            self._update_on_kvstore = False
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """rescale by 1/batch_size, allreduce, update (reference flow).

        With ``amp.init_trainer`` attached, the gradient rescale additionally
        divides by the current loss scale (so updates see unscaled grads) and
        non-finite gradients skip the update for this step while the dynamic
        scaler backs off (reference amp trainer flow).
        """
        self._init_kvstore()
        if _chaos._ACTIVE:
            _chaos.hit("trainer.step")  # named chaos site (mid-run faults)
        if _ttrace._ENABLED:
            # StepClock (ISSUE 10): open the step — the gap since the last
            # step (forward/backward/user code) and any pending data-wait
            # notes from the DataLoader fold into this step's attribution
            _sclock.STEP_CLOCK.begin_step()
        with _tel.span("trainer.step", "trainer", batch_size=batch_size) as sp:
            scaler = getattr(self, "_amp_loss_scaler", None)
            base_scale = getattr(self, "_amp_original_scale", self._scale)
            scale = (base_scale if scaler is not None
                     else self._scale) / batch_size
            if scaler is not None:
                if not getattr(self, "_amp_grads_unscaled", False):
                    # amp.unscale() already divided the grads in place — don't
                    # fold 1/loss_scale into the rescale a second time
                    scale /= scaler.loss_scale
                self._amp_grads_unscaled = False
                # overflow check BEFORE any update runs: with update_on_kvstore
                # the store applies the optimizer inside _allreduce_grads, so a
                # post-reduce check would be too late (inf in any replica makes
                # the reduced grad inf, so pre-reduce detection is equivalent)
                grads = [g for p in self._params if p.grad_req != "null"
                         and p._data is not None for g in p.list_grad()]
                if scaler.has_overflow(grads):
                    self._scale = base_scale
                    return  # skip step; dynamic scaler backed off
            self._optimizer.rescale_grad = scale
            self._allreduce_grads(allow_flat=True)
            if not self._update_on_kvstore:
                self._update(ignore_stale_grad)
            if scaler is not None:
                self._scale = base_scale
        if sp is not _tel.NULL_SPAN:
            _M_STEPS.inc()
            _M_STEP_SECONDS.observe(sp.duration_s)
            _sclock.STEP_CLOCK.end_step()

    def allreduce_grads(self):
        self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "allreduce_grads() is invalid with update_on_kvstore=True "
                "(reference contract)")
        self._allreduce_grads()

    def _allreduce_grads(self, allow_flat=False):
        # allow_flat only inside step(): the public allreduce_grads()
        # contract is "reduced grads land in the grad buffers", which the
        # flat handoff deliberately skips
        self._flat_handoff = None
        if self._kvstore is None:
            return
        sp = _tel.span("trainer.allreduce", "trainer",
                       update_on_kvstore=self._update_on_kvstore)
        try:
            self._allreduce_grads_impl(sp, allow_flat)
        finally:
            if sp is not _tel.NULL_SPAN:
                # comms phase for the StepClock verdict (every internal
                # return path lands here with the span already closed)
                _sclock.STEP_CLOCK.note("comms", sp.duration_s)

    def _allreduce_grads_impl(self, sp, allow_flat):
        with sp:
            if self._update_on_kvstore:
                # per-key: the store runs the optimizer inside push and pull
                # broadcasts the updated WEIGHTS (no fused analog — the
                # fusion layer reduces gradients only).  mesh_reduced
                # params cannot be honored here: skipping the push would
                # skip the store's optimizer update too, and pushing
                # double-counts the mesh's psum — fail loudly.
                from .. import config as _cfg
                if _cfg.get_int("MXNET_SHARDING_SKIP_ALLREDUCE", 1) \
                        and any(p.mesh_reduced for p in self._params
                                if p.grad_req != "null"):
                    raise MXNetError(
                        "update_on_kvstore=True cannot honor "
                        "Parameter.mesh_reduced: the store reduces inside "
                        "push, double-counting gradients the mesh already "
                        "reduced.  Use update_on_kvstore=False, clear the "
                        "mesh_reduced flags, or set "
                        "MXNET_SHARDING_SKIP_ALLREDUCE=0 to accept the "
                        "unconditional reduction.")
                for i, p in enumerate(self._params):
                    if p.grad_req == "null":
                        continue
                    grads = p.list_grad()
                    self._kvstore.push(i, grads if len(grads) > 1
                                       else grads[0])
                    datas = p.list_data()
                    self._kvstore.pull(i, datas if len(datas) > 1
                                       else datas[0])
                return
            # dense path: hand the WHOLE grad list to the kvstore in one
            # call; it buckets dense uncompressed keys into flat buffers
            # (kvstore/fusion.py) and falls back per key for the rest,
            # bit-identically
            #
            # sharding engine (ISSUE 8): params whose gradients a mesh
            # computation already reduced (Parameter.mesh_reduced — GSPMD
            # psum over the data axis inside the jit) skip the LOCAL
            # reduction here, which would double-count over the same
            # devices.  Dist stores still reduce everything: the mesh
            # spans one process, the dist psum spans the job.
            from .. import config as _cfg
            skip_reduced = (
                not hasattr(self._kvstore, "_ensure_dist")
                and _cfg.get_int("MXNET_SHARDING_SKIP_ALLREDUCE", 1))
            keys, vals = [], []
            n_skipped = 0
            for i, p in enumerate(self._params):
                if p.grad_req == "null":
                    continue
                if skip_reduced and p.mesh_reduced:
                    n_skipped += 1
                    continue
                grads = p.list_grad()
                keys.append(i)
                vals.append(grads if len(grads) > 1 else grads[0])
            if n_skipped and _ttrace._ENABLED:
                from .. import sharding as _sh
                _sh._M_SKIPPED_ALLREDUCE.inc(n_skipped)
            if not keys:
                return
            if allow_flat and self._fused_kind() is not None \
                    and hasattr(self._kvstore, "pushpull_flat"):
                # fused-optimizer handoff: reduced buckets stay FLAT and
                # feed the donated optimizer update directly (no
                # unflatten/reflatten HBM round trip).  Bucketed keys'
                # grad buffers keep their local pre-reduction values.
                res = self._kvstore.pushpull_flat(keys, vals, vals)
                if res is not None:
                    self._flat_handoff = res
                    return
            if hasattr(self._kvstore, "pushpull_list"):
                self._kvstore.pushpull_list(keys, vals, vals)
            else:  # duck-typed store: reference per-key push+pull
                for k, v in zip(keys, vals):
                    self._kvstore.push(k, v)
                    self._kvstore.pull(k, v)

    def update(self, batch_size, ignore_stale_grad=False):
        self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "update() is invalid with update_on_kvstore=True "
                "(reference contract)")
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):  # noqa: ARG002
        with _tel.span("trainer.optimizer", "trainer") as sp:
            self._update_impl()
        if sp is not _tel.NULL_SPAN:
            _sclock.STEP_CLOCK.note("optimizer", sp.duration_s)

    def _fused_kind(self):
        """'adam'/'sgd' when the flat-buffer fused optimizer path applies
        to this step, else None (knob off, unsupported optimizer, or the
        kvstore owns the update)."""
        if self._update_on_kvstore:
            return None
        from .. import optimizer_fusion as _fus
        if not _fus.fusion_active(self._optimizer):
            return None
        return _fus.supported_kind(self._optimizer)

    def _update_impl(self):
        optzr = self._optimizer
        # a stashed flat handoff MUST be consumed fused — its keys' grad
        # buffers were deliberately left unreduced
        if self._flat_handoff is not None or self._fused_kind() is not None:
            self._update_fused()
            return
        agg = getattr(optzr, "aggregate_num", 0)
        if agg > 1 and len(self._updaters) == 1 \
                and hasattr(optzr, "update_multi"):
            self._update_aggregated(agg)
            return
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data is None:
                continue
            # replicas must see the SAME step count t (Adam bias correction,
            # lr schedules): snapshot the shared optimizer's counters for
            # this index before the first replica and restore for each
            # subsequent one, so one logical step advances t exactly once
            snap_count = optzr._index_update_count.get(i)
            snap_num = optzr.num_update
            for j, (upd, w, g) in enumerate(
                    zip(self._updaters, p.list_data(), p.list_grad())):
                if j > 0:
                    if snap_count is None:
                        optzr._index_update_count.pop(i, None)
                    else:
                        optzr._index_update_count[i] = snap_count
                    optzr.num_update = snap_num
                upd(i, g, w)

    def _update_fused(self):
        """Flat-buffer fused optimizer step (optimizer_fusion): dense
        params update in ONE donated jitted dispatch per dtype bucket —
        fed flat reduced-gradient buffers directly when the kvstore's
        fused allreduce handed them over — while sparse/row-sparse params
        keep the per-key path, exactly like the kvstore fused fallback
        rules.  Multi-replica: every replica applies the same update with
        its own updater's states (step count t advances once)."""
        from .. import optimizer_fusion as _fus
        optzr = self._optimizer
        handoff, self._flat_handoff = self._flat_handoff, None
        dense, perkey = [], []
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data is None:
                continue
            if p.stype == "default" and p.grad_stype == "default":
                dense.append(i)
            else:
                perkey.append(i)
        enabled = _ttrace._ENABLED
        if perkey and enabled:
            _fus.record_fallback(len(perkey))
        covered = set()
        for keys_list, _shapes, _sizes, _flat in (handoff or ()):
            covered.update(keys_list)
        rest = [i for i in dense if i not in covered]
        datas = {i: self._params[i].list_data() for i in dense + perkey}
        grads = {i: self._params[i].list_grad() for i in rest + perkey}
        # replicas must see the SAME step count t: snapshot the shared
        # optimizer's counters before the first replica and restore for
        # each subsequent one (the fused analog of _update_impl's
        # per-index snapshotting)
        snap_counts = dict(optzr._index_update_count)
        snap_num = optzr.num_update
        for j, upd in enumerate(self._updaters):
            if j > 0:
                optzr._index_update_count.clear()
                optzr._index_update_count.update(snap_counts)
                optzr.num_update = snap_num
            for keys_list, shapes, sizes, flat in (handoff or ()):
                ks = [i for i in keys_list if j < len(datas[i])]
                if len(ks) != len(keys_list):
                    if not ks:
                        continue
                    raise MXNetError(
                        "fused flat handoff spans params with unequal "
                        "replica counts; use MXNET_OPTIMIZER_FUSED=0")
                upd.call_fused(ks, None, [datas[i][j] for i in ks],
                               flat_grad=flat, shapes=shapes, sizes=sizes)
            rj = [i for i in rest if j < len(datas[i])]
            if rj:
                upd.call_fused(rj, [grads[i][j] for i in rj],
                               [datas[i][j] for i in rj])
            if enabled and (handoff or rj):
                _fus.record_update()   # one per replica step, not per call
            for i in perkey:
                if j < len(datas[i]):
                    upd(i, grads[i][j], datas[i][j])

    def _update_aggregated(self, agg):
        """Multi-tensor fast path (reference optimizer aggregation over
        multi_sgd_update kernels, src/operator/optimizer_op.cc): groups of
        up to ``agg`` same-dtype params update in ONE registry dispatch
        instead of one per param.  Single-replica only — the multi-ctx
        path keeps the per-param loop with its step-count snapshotting."""
        upd = self._updaters[0]
        group, group_dt = [], None
        def flush():
            nonlocal group, group_dt
            if group:
                upd.call_multi([i for i, _, _ in group],
                               [g for _, _, g in group],
                               [w for _, w, _ in group])
            group, group_dt = [], None
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data is None:
                continue
            w, g = p.list_data()[0], p.list_grad()[0]
            if group and (w.dtype != group_dt or len(group) >= agg):
                flush()
            group.append((i, w, g))
            group_dt = w.dtype
        flush()

    def save_states(self, fname):
        """With update_on_kvstore the optimizer state lives in the store
        (reference delegates to kvstore.save_optimizer_states)."""
        self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
            return
        with open(fname, "wb") as f:
            f.write(self._updaters[0].get_states())

    def load_states(self, fname):
        self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, "rb") as f:
            data = f.read()
        for u in self._updaters:
            u.set_states(data)
