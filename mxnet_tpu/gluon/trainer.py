"""gluon.Trainer (reference python/mxnet/gluon/trainer.py, P6).

API parity: Trainer(params, optimizer, optimizer_params, kvstore,
update_on_kvstore), ``step(batch_size)``, ``allreduce_grads()``, ``update()``,
``save_states/load_states``, ``learning_rate`` property.

TPU-native: with kvstore='device'/'local' on one process the gradient
reduction is an XLA psum over the data-parallel mesh axis (or a no-op on a
single chip); with 'dist_tpu_sync' the psum spans hosts over ICI/DCN (see
mxnet_tpu.kvstore).  The optimizer always runs on device (the reference moves
it to the PS server in dist mode — here the server role does not exist for
dense training, SURVEY §5.8).
"""

from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):  # noqa: ARG002
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("first argument must be a list/dict of Parameters")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p}")
            self._param2idx[p.name] = i
            self._params.append(p)
            p._trainer = self
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params and set(optimizer_params) - {"rescale_grad"}:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        if self._kv_initialized:
            return
        kvt = self._kvstore_type
        if kvt is None or kvt is False:
            self._kvstore = None
        elif isinstance(kvt, str):
            from .. import kvstore as kvs
            if kvt in ("local", "device", "nccl") and kvs.num_data_devices() <= 1:
                self._kvstore = None  # single device: reduction is identity
            else:
                self._kvstore = kvs.create(kvt)
        else:
            self._kvstore = kvt
        if self._kvstore is not None:
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    self._kvstore.init(i, p.data())
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """rescale by 1/batch_size, allreduce, update (reference flow)."""
        self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, p in enumerate(self._params):
            if p.grad_req != "null":
                self._kvstore.push(i, p.grad())
                self._kvstore.pull(i, p.grad())

    def update(self, batch_size, ignore_stale_grad=False):
        self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):  # noqa: ARG002
        updater = self._updaters[0]
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data is None:
                continue
            updater(i, p.grad(), p.data())

    def save_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self._updaters[0].get_states())

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self._updaters[0].set_states(f.read())
