"""gluon.loss (reference python/mxnet/gluon/loss.py, P7): the full zoo —
L2/L1/SigmoidBCE/SoftmaxCE/KL/CTC/Huber/Hinge/SquaredHinge/Logistic/Triplet/
Cosine.  CTC lowers to an XLA-friendly log-alpha recursion (optax.ctc_loss)
instead of the reference's warp-ctc/cudnn kernels."""

from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss",
           "CosineEmbeddingLoss", "LabelSmoothedCELoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return (f"{type(self).__name__}(batch_axis={self._batch_axis}, "
                f"w={self._weight})")

    def _mean_over_non_batch(self, F, loss):
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return F.mean(loss, axis=axes) if axes else loss


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return self._mean_over_non_batch(F, loss)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_non_batch(F, loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + \
                    F.Activation(-F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + F.broadcast_mul(pos_weight - 1, label)
                loss = pred - pred * label + log_weight * \
                    (F.Activation(-F.abs(pred), act_type="softrelu")
                     + F.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label
                         + F.log(1. - pred + eps) * (1. - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label, pos_weight)
                         + F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_non_batch(F, loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_non_batch(F, loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class LabelSmoothedCELoss(Loss):
    """Label-smoothed softmax CE over sparse int labels — the MT training
    loss (GluonNLP LabelSmoothing + SoftmaxCEMaskedLoss pair, collapsed
    into one fused computation: the smoothed target distribution is never
    materialized).

    loss_i = (1-a) * nll_i + a * mean_v(-logp_i[v]),  a = ``smoothing``.
    Positions whose label equals ``ignore_index`` (target padding)
    contribute zero and are excluded from the mean when ``normalize``.
    Returns per-BATCH-ROW loss like the other losses here (mean over
    non-batch axes, padding-aware)."""

    def __init__(self, smoothing=0.1, ignore_index=None, axis=-1,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._smoothing = smoothing
        self._ignore = ignore_index
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        logp = F.log_softmax(pred, axis=self._axis)
        nll = -F.pick(logp, label, axis=self._axis)        # (B, L...)
        uniform = -F.mean(logp, axis=self._axis)
        loss = (1.0 - self._smoothing) * nll + self._smoothing * uniform
        if self._ignore is not None:
            axes = tuple(i for i in range(loss.ndim)
                         if i != self._batch_axis)
            valid = (label != self._ignore).astype(loss.dtype)
            loss = _apply_weighting(F, loss * valid, self._weight,
                                    sample_weight)
            if not axes:
                return loss
            n = F.sum(valid, axis=axes)          # max(count, 1) floor
            return F.sum(loss, axis=axes) / F.maximum(n, F.ones_like(n))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_non_batch(F, loss)


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_non_batch(F, loss)


class CTCLoss(Loss):
    """Connectionist temporal classification.

    Layouts follow the reference (src/operator/nn/ctc_loss.cc): default
    pred (T, N, C) via layout='NTC' input convention on the Gluon layer.
    Blank label convention: last class index C-1 ('last') or 0 ('first').
    """

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 blank_label="last", **kwargs):
        # upstream gluon CTCLoss fixes the blank at index C-1 ('last');
        # blank_label is exposed as an extension for 'first'-convention
        # checkpoints (labels then 1-based, 0-padded)
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout
        self._blank_label = blank_label

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        # routed through the registered `ctc_loss` op (nn/ctc_loss.cc
        # analog) so the imperative tape records a proper vjp
        if self._layout == "NTC":
            pred = pred.swapaxes(0, 1)            # op contract: (T, N, C)
        if self._label_layout == "TN":
            label = label.swapaxes(0, 1)
        if label_lengths is not None and pred_lengths is None:
            # op wrappers drop None positionals, which would shift
            # label_lengths into the data_lengths slot — materialize the
            # trivial full-length data_lengths instead
            pred_lengths = F.full((pred.shape[1],), pred.shape[0],
                                  dtype="int32")
        loss = F.ctc_loss(pred, label, pred_lengths, label_lengths,
                          use_data_lengths=pred_lengths is not None,
                          use_label_lengths=label_lengths is not None,
                          blank_label=self._blank_label)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_non_batch(F, loss)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_non_batch(F, loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_non_batch(F, loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_non_batch(F, loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative,
                       sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        axes = tuple(range(1, pred.ndim))
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=axes)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = _reshape_like(F, input1, input2)
        eps = 1e-12
        num = F.sum(input1 * input2, axis=-1)
        den = F.sqrt(F.sum(F.square(input1), axis=-1)
                     * F.sum(F.square(input2), axis=-1) + eps)
        cos = num / den
        label = label.reshape(cos.shape)
        loss = F.where(label == 1, 1.0 - cos,
                       F.relu(cos - self._margin))
        return _apply_weighting(F, loss, self._weight, sample_weight)
