"""Estimator event handlers (reference
gluon/contrib/estimator/event_handler.py).

Mixin interfaces: TrainBegin/TrainEnd/EpochBegin/EpochEnd/BatchBegin/
BatchEnd — the Estimator calls each handler's hook with itself as
``estimator``.  Stock handlers: StoppingHandler (max epoch/batch),
LoggingHandler (per-interval metric logs), CheckpointHandler (save
params/trainer each epoch, keep best), ValidationHandler (periodic
evaluate), EarlyStoppingHandler (monitor-based stop).
"""

from __future__ import annotations

import logging
import os
import time

import numpy as _np

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd", "BatchBegin",
           "BatchEnd", "StoppingHandler", "LoggingHandler",
           "CheckpointHandler", "EarlyStoppingHandler", "ValidationHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop at max_epoch or max_batch (reference StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True
        return self.stop_training

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True
        return self.stop_training


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    """Log metrics per epoch (and every ``log_interval`` batches)."""

    def __init__(self, log_interval="epoch", metrics=None):
        self.log_interval = log_interval
        self.metrics = metrics
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0
        self.logger = logging.getLogger("mxnet_tpu.estimator")

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        self.logger.info("Training done in %.1fs",
                         time.time() - self.train_start)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()
        self.batch_index = 0
        self.processed_samples = 0

    def batch_end(self, estimator, batch=None, **kwargs):
        self.batch_index += 1
        if isinstance(self.log_interval, int) and \
                self.batch_index % self.log_interval == 0:
            msg = " ".join(f"{n}={v:.4f}" for n, v in
                           self._metric_values(estimator))
            self.logger.info("epoch %d batch %d %s", self.current_epoch,
                             self.batch_index, msg)

    def epoch_end(self, estimator, *args, **kwargs):
        msg = " ".join(f"{n}={v:.4f}" for n, v in
                       self._metric_values(estimator))
        self.logger.info("[Epoch %d] time %.1fs %s", self.current_epoch,
                         time.time() - self.epoch_start, msg)
        self.current_epoch += 1

    def _metric_values(self, estimator):
        metrics = self.metrics if self.metrics is not None \
            else estimator.train_metrics
        out = []
        for m in metrics:
            n, v = m.get()
            if isinstance(n, (list, tuple)):
                out.extend(zip(n, v))
            else:
                out.append((n, v))
        return out


class CheckpointHandler(TrainBegin, EpochEnd):
    """Save params (+trainer states) each epoch; track the best by a
    monitored metric (reference CheckpointHandler, simplified to the
    epoch cadence)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 mode="min", save_best=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self._cmp = (lambda a, b: a < b) if mode == "min" \
            else (lambda a, b: a > b)
        self.best = None
        self.current_epoch = 0
        os.makedirs(model_dir, exist_ok=True)

    def train_begin(self, estimator, *args, **kwargs):
        self.current_epoch = 0
        self.best = None

    def epoch_end(self, estimator, *args, **kwargs):
        prefix = os.path.join(self.model_dir, self.model_prefix)
        estimator.net.save_parameters(
            f"{prefix}-epoch{self.current_epoch}.params")
        if estimator.trainer is not None:
            estimator.trainer.save_states(
                f"{prefix}-epoch{self.current_epoch}.states")
        if self.save_best and self.monitor is not None:
            _, val = self.monitor.get()
            if self.best is None or self._cmp(val, self.best):
                self.best = val
                estimator.net.save_parameters(f"{prefix}-best.params")
        self.current_epoch += 1


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run evaluation every ``epoch_period`` epochs (reference
    ValidationHandler)."""

    def __init__(self, val_data, eval_fn, epoch_period=1,
                 event_handlers=None):  # noqa: ARG002
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.current_epoch = 0
        # run validation first so monitors (early stop) see fresh values
        self.priority = -1

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and \
                self.current_epoch % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop when the monitored metric stops improving (reference
    EarlyStoppingHandler)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="min",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = abs(min_delta)
        self.patience = patience
        self.baseline = baseline
        self._sign = -1 if mode == "min" else 1
        self.logger = logging.getLogger("mxnet_tpu.estimator")

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.stopped_epoch = None
        self.current_epoch = 0
        self.best = self.baseline if self.baseline is not None else \
            -self._sign * _np.inf

    def epoch_end(self, estimator, *args, **kwargs):
        _, val = self.monitor.get()
        improved = self._sign * (val - self.best) > self.min_delta \
            if _np.isfinite(self.best) else True
        stop = False
        if improved:
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                stop = True
        self.current_epoch += 1
        return stop

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch is not None:
            self.logger.info("Early stopping at epoch %d",
                             self.stopped_epoch)
