"""gluon.contrib.estimator (reference gluon/contrib/estimator/, P10)."""

from .estimator import Estimator  # noqa: F401
from .event_handler import (TrainBegin, TrainEnd, EpochBegin, EpochEnd,  # noqa: F401
                            BatchBegin, BatchEnd, StoppingHandler,
                            LoggingHandler, CheckpointHandler,
                            EarlyStoppingHandler, ValidationHandler)
