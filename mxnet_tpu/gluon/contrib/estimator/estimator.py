"""Estimator — Keras-like fit loop (reference
gluon/contrib/estimator/estimator.py, P10).

Wraps net/loss/metrics/trainer and drives epochs of
forward-backward-step with the event-handler protocol; ``evaluate``
runs validation metrics.  The loop mirrors the reference: metrics update
per batch, handlers may stop training by returning True from their
hooks.
"""

from __future__ import annotations

from ....base import MXNetError
from .... import metric as _metric
from ... import Trainer
from ... import loss as _loss
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            LoggingHandler, StoppingHandler, TrainBegin,
                            TrainEnd)

__all__ = ["Estimator"]


class Estimator:
    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 trainer=None, context=None):
        self.net = net
        if not isinstance(loss, _loss.Loss):
            raise MXNetError("loss must be a gluon.loss.Loss")
        self.loss = loss
        self.train_metrics = _as_metrics(train_metrics)
        self.val_metrics = _as_metrics(val_metrics) \
            if val_metrics is not None else \
            [_metric.create(m.name) for m in self.train_metrics] or []
        self.context = context
        self.trainer = trainer if trainer is not None else Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        # loss tracked as a metric row like the reference
        self.train_loss_metric = _metric.Loss(
            f"train_{type(loss).__name__.lower()}")
        self.val_loss_metric = _metric.Loss(
            f"val_{type(loss).__name__.lower()}")

    # -- evaluation ----------------------------------------------------------
    def evaluate(self, val_data):
        for m in self.val_metrics:
            m.reset()
        self.val_loss_metric.reset()
        for batch in val_data:
            data, label = _split_batch(batch)
            out = self.net(data)
            l = self.loss(out, label)
            self.val_loss_metric.update(None, l)
            for m in self.val_metrics:
                m.update(label, out)
        return [self.val_loss_metric.get()] + \
            [m.get() for m in self.val_metrics]

    # -- training ------------------------------------------------------------
    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None):
        from .... import autograd
        if epochs is None and batches is None:
            raise MXNetError("fit needs epochs or batches")
        stopper = StoppingHandler(max_epoch=epochs, max_batch=batches)
        handlers = [stopper] + list(event_handlers or [])
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler())

        def fire(cls, hook, *args, **kwargs):
            stop = False
            for h in handlers:
                if isinstance(h, cls):
                    if getattr(h, hook)(self, *args, **kwargs):
                        stop = True
            return stop

        fire(TrainBegin, "train_begin")
        stop = False
        while not stop:
            for m in self.train_metrics:
                m.reset()
            self.train_loss_metric.reset()
            fire(EpochBegin, "epoch_begin")
            for batch in train_data:
                fire(BatchBegin, "batch_begin", batch=batch)
                data, label = _split_batch(batch)
                with autograd.record():
                    out = self.net(data)
                    l = self.loss(out, label)
                l.backward()
                bs = data.shape[0]
                self.trainer.step(bs)
                self.train_loss_metric.update(None, l)
                for m in self.train_metrics:
                    m.update(label, out)
                if fire(BatchEnd, "batch_end", batch=batch):
                    stop = True
                    break
            if val_data is not None:
                self.evaluate(val_data)
            if fire(EpochEnd, "epoch_end"):
                stop = True
            if hasattr(train_data, "reset"):
                train_data.reset()
        fire(TrainEnd, "train_end")
        return self


def _as_metrics(metrics):
    if metrics is None:
        return []
    if isinstance(metrics, (_metric.EvalMetric,)):
        return [metrics]
    return [m if isinstance(m, _metric.EvalMetric) else _metric.create(m)
            for m in metrics]


def _split_batch(batch):
    if isinstance(batch, (list, tuple)) and len(batch) >= 2:
        return batch[0], batch[1]
    data = getattr(batch, "data", None)
    label = getattr(batch, "label", None)
    if data is not None and label is not None:
        return data[0], label[0]
    raise MXNetError("batch must be (data, label) or a DataBatch")
