"""gluon.contrib (reference python/mxnet/gluon/contrib/)."""

from . import estimator  # noqa: F401
from . import nn  # noqa: F401 — SyncBatchNorm/Identity/Concurrent
from .moe import SparseMoE  # noqa: F401 — MoE/expert parallelism (new vs reference)
