"""gluon.contrib (reference python/mxnet/gluon/contrib/)."""

from . import estimator  # noqa: F401
from .moe import SparseMoE  # noqa: F401 — MoE/expert parallelism (new vs reference)
