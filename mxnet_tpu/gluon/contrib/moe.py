"""Mixture-of-Experts layers with expert parallelism.

NEW capability relative to the reference: SURVEY §2.4 flags expert
parallelism / MoE ABSENT upstream (no MoE layers or ops anywhere in
apache/incubator-mxnet 1.x).  The TPU-native design follows the
GShard/Switch dense-dispatch recipe — static shapes and one-hot einsum
dispatch so XLA tiles everything onto the MXU, no dynamic gather/scatter:

 - router: per-token softmax over experts, top-k choices (k=1 Switch,
   k=2 GShard default);
 - capacity: each expert processes at most C = ceil(k·N/E · capacity_factor)
   tokens per batch; overflow tokens fall through the residual (standard
   GShard semantics);
 - dispatch/combine are (N, E, C) one-hot masks contracted with einsum —
   the whole layer is three batched matmuls plus elementwise glue;
 - expert parallelism: the stacked expert weights (E, …) carry
   ``Parameter.sharding = (expert_axis, …)`` hints; under
   ``parallel.TrainStep`` on a mesh with that axis, GSPMD shards experts
   across devices and inserts the all-to-alls over ICI;
 - auxiliary load-balance loss (Switch eq. 4): E · Σ_e f_e · p_e, returned
   alongside the output so callers add ``aux_weight * aux`` to their loss.
"""

from __future__ import annotations

import math

from ..block import HybridBlock
from ...base import MXNetError

__all__ = ["SparseMoE"]


class SparseMoE(HybridBlock):
    """Sparsely-gated mixture-of-experts FFN (drop-in for a transformer FFN).

    Parameters
    ----------
    units : int — model width d.
    hidden_size : int — per-expert FFN hidden width.
    num_experts : int — E.
    num_experts_per_token : int — k (1 = Switch, 2 = GShard).
    capacity_factor : float — slack over the perfectly-balanced per-expert
        load; tokens beyond an expert's capacity are dropped (identity
        residual path, per GShard).
    activation : 'gelu' | 'relu' | 'silu'.
    expert_axis : mesh-axis name the expert dim shards over ('ep').

    ``__call__(x) -> (y, aux_loss)`` with x (B, L, units) or (N, units);
    y has x's shape, aux_loss is a scalar.
    """

    def __init__(self, units, hidden_size, num_experts,
                 num_experts_per_token=2, capacity_factor=1.25,
                 activation="gelu", expert_axis="ep", **kwargs):
        super().__init__(**kwargs)
        if num_experts_per_token > num_experts:
            raise MXNetError("num_experts_per_token > num_experts")
        self._units = units
        self._hidden = hidden_size
        self._E = int(num_experts)
        self._k = int(num_experts_per_token)
        self._cf = float(capacity_factor)
        self._act = activation
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(units, num_experts), init=None)
            self.expert_w1 = self.params.get(
                "expert_w1", shape=(num_experts, units, hidden_size),
                init=None)
            self.expert_b1 = self.params.get(
                "expert_b1", shape=(num_experts, hidden_size), init="zeros")
            self.expert_w2 = self.params.get(
                "expert_w2", shape=(num_experts, hidden_size, units),
                init=None)
            self.expert_b2 = self.params.get(
                "expert_b2", shape=(num_experts, units), init="zeros")
        # expert-parallel sharding hints (consumed by parallel.TrainStep)
        for p in (self.expert_w1, self.expert_b1, self.expert_w2,
                  self.expert_b2):
            p.sharding = (expert_axis,) + (None,) * (len(p.shape) - 1)

    def _activate(self, F, h):
        if self._act == "relu":
            return F.relu(h)
        if self._act == "silu":
            return F.silu(h)
        return F.gelu(h)

    def hybrid_forward(self, F, x, gate_weight=None, expert_w1=None,
                       expert_b1=None, expert_w2=None, expert_b2=None):
        E, k = self._E, self._k
        in_shape = x.shape
        xf = F.reshape(x, shape=(-1, self._units))       # (N, d)
        N = xf.shape[0]
        C = max(1, int(math.ceil(k * N / E * self._cf)))

        logits = F.dot(xf, gate_weight)                  # (N, E)
        probs = F.softmax(logits, axis=-1)
        _, topi = F.topk(probs, k=k, ret_typ="both", axis=-1)  # (N, k)

        # sequential-position dispatch (GShard): choice-0 tokens claim
        # capacity slots first, later choices are offset by earlier counts.
        # Gate values are re-gathered from `probs` via the one-hot masks so
        # the router weight receives task-loss gradient (topk's outputs are
        # detached on the imperative tape — topk is non-differentiable).
        disps, raw_gates = [], []
        prev_count = F.zeros((1, E))
        f_frac = None                                    # top-1 load fraction
        for j in range(k):
            idx_j = F.reshape(F.slice_axis(topi, axis=1, begin=j, end=j + 1),
                              shape=(-1,))
            oh = F.one_hot(idx_j, depth=E)               # (N, E)
            if j == 0:
                f_frac = F.mean(oh, axis=0)              # (E,)
            pos = F.cumsum(oh, axis=0) - oh + prev_count  # 0-based slot
            prev_count = prev_count + F.sum(oh, axis=0, keepdims=True)
            slot = F.sum(pos * oh, axis=-1)              # (N,)
            keep = (slot < C).astype(xf.dtype)           # capacity mask
            slot_oh = F.one_hot(
                F.clip(slot, a_min=0, a_max=C - 1).astype("int32"),
                depth=C)                                 # (N, C)
            disps.append(
                F.expand_dims(oh * F.expand_dims(keep, axis=1), axis=2)
                * F.expand_dims(slot_oh, axis=1))        # (N, E, C)
            raw_gates.append(F.sum(probs * oh, axis=-1))  # (N,) differentiable

        # Switch (k=1) scales by the raw router prob — that's the router's
        # learning signal; GShard (k>1) normalizes over the chosen experts
        if k == 1:
            gate_vals = [raw_gates[0]]
        else:
            denom = raw_gates[0]
            for g in raw_gates[1:]:
                denom = denom + g
            gate_vals = [g / denom for g in raw_gates]

        combine = None
        for disp_j, gate_j in zip(disps, gate_vals):
            comb_j = disp_j * F.reshape(gate_j, shape=(-1, 1, 1))
            combine = comb_j if combine is None else combine + comb_j
        dispatch = (combine > 0).astype(xf.dtype)        # (N, E, C)

        # expert computation: three MXU-friendly batched contractions
        expert_in = F.einsum(dispatch, xf, subscripts="nec,nd->ecd")
        h = self._activate(
            F, F.einsum(expert_in, expert_w1, subscripts="ecd,edh->ech")
            + F.expand_dims(expert_b1, axis=1))
        out = F.einsum(h, expert_w2, subscripts="ech,ehd->ecd") \
            + F.expand_dims(expert_b2, axis=1)
        y = F.einsum(combine, out, subscripts="nec,ecd->nd")
        y = F.reshape(y, shape=in_shape)

        # Switch load-balance loss: E * sum_e (token fraction_e * prob mass_e)
        aux = F.sum(f_frac * F.mean(probs, axis=0)) * E
        return y, aux
