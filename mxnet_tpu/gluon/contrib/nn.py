"""gluon.contrib.nn — contributed layers.

Reference: python/mxnet/gluon/contrib/nn/basic_layers.py (SyncBatchNorm,
HybridConcurrent, Identity, …).
"""

from __future__ import annotations

from ..nn.basic_layers import BatchNorm, HybridSequential
from ..block import HybridBlock

__all__ = ["SyncBatchNorm", "Identity", "Concurrent", "HybridConcurrent"]


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (reference contrib/nn ::
    SyncBatchNorm over src/operator/contrib/sync_batch_norm.cc).

    TPU-native statement of the contract: the reference synchronizes batch
    statistics across the ``num_devices`` data-parallel workers with a
    key-based barrier.  Under this framework's performance path
    (``parallel.TrainStep`` — one jitted SPMD program over the mesh) the
    batch axis is GLOBAL: ``mean``/``var`` reduce over the full sharded
    batch and GSPMD inserts the cross-device psum, so plain BatchNorm
    already IS sync-BN — no extra op, no barrier, no second code path.
    This subclass exists for API parity and for documentation of that
    absorption; ``num_devices`` is accepted and recorded.

    The legacy per-ctx replica path (gluon.utils.split_and_load + per-ctx
    forwards) computes per-replica statistics like upstream's plain
    BatchNorm would; use TrainStep when synchronized statistics matter.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=
                         running_variance_initializer,
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class Identity(HybridBlock):
    """Pass-through block (reference contrib/nn :: Identity)."""

    def hybrid_forward(self, F, x):  # noqa: ARG002
        return x


class Concurrent(HybridSequential):
    """Run children on the same input and concat outputs along ``axis``
    (reference contrib/nn :: Concurrent).  Implemented via hybrid_forward
    so hybridize()/export() work (HybridConcurrent contract)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis

    def hybrid_forward(self, F, x):
        outs = [child(x) for child in self._children.values()]
        return F.concat(*outs, dim=self._axis)


HybridConcurrent = Concurrent
