"""gluon.data.vision datasets (reference gluon/data/vision/datasets.py):
MNIST / FashionMNIST / CIFAR10 / CIFAR100 / ImageRecordDataset /
ImageFolderDataset.

This environment has no network egress, so datasets read from a local root
only (standard file formats: idx-ubyte for MNIST, python pickle batches for
CIFAR); a missing root raises with a clear message instead of downloading.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as _np

from ....base import MXNetError
from ... import data as _data  # noqa: F401
from ..dataset import Dataset, ArrayDataset


def _open_maybe_gz(path):
    if os.path.exists(path):
        return open(path, "rb")
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    raise FileNotFoundError(path)


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._root = os.path.expanduser(root)
        self._transform = transform
        self._data = None
        self._label = None
        if not os.path.isdir(self._root):
            raise MXNetError(
                f"dataset root {self._root} does not exist; this build has "
                "no network egress — place the dataset files there manually")
        self._get_data()

    def __getitem__(self, idx):
        from .... import ndarray as nd
        x = nd.array(self._data[idx])
        y = self._label[idx]
        if self._transform is not None:
            return self._transform(x, y)
        return x, y

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """reference gluon/data/vision/datasets.py :: MNIST (idx-ubyte files)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        self._train_data = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
        self._test_data = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")
        super().__init__(root, transform)

    def _get_data(self):
        images, labels = self._train_data if self._train else self._test_data
        with _open_maybe_gz(os.path.join(self._root, labels)) as f:
            struct.unpack(">II", f.read(8))
            self._label = _np.frombuffer(f.read(), dtype=_np.uint8) \
                .astype(_np.int32)
        with _open_maybe_gz(os.path.join(self._root, images)) as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = _np.frombuffer(f.read(), dtype=_np.uint8)
            self._data = data.reshape(n, rows, cols, 1)


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _batches(self):
        if self._train:
            return [f"data_batch_{i}" for i in range(1, 6)]
        return ["test_batch"]

    def _get_data(self):
        # accepts both the python-pickle layout (cifar-10-batches-py) and a
        # flat root containing the batch files
        base = self._root
        sub = os.path.join(base, "cifar-10-batches-py")
        if os.path.isdir(sub):
            base = sub
        data, labels = [], []
        for b in self._batches():
            with open(os.path.join(base, b), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            data.append(d[b"data"])
            labels.extend(d[b"labels"])
        data = _np.concatenate(data).reshape(-1, 3, 32, 32)
        self._data = data.transpose(0, 2, 3, 1)  # HWC like the reference
        self._label = _np.asarray(labels, dtype=_np.int32)


class CIFAR100(_DownloadedDataset):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._train = train
        self._fine = fine_label
        super().__init__(root, transform)

    def _get_data(self):
        base = self._root
        sub = os.path.join(base, "cifar-100-python")
        if os.path.isdir(sub):
            base = sub
        fname = "train" if self._train else "test"
        with open(os.path.join(base, fname), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        data = d[b"data"].reshape(-1, 3, 32, 32)
        self._data = data.transpose(0, 2, 3, 1)
        key = b"fine_labels" if self._fine else b"coarse_labels"
        self._label = _np.asarray(d[key], dtype=_np.int32)


class ImageRecordDataset(Dataset):
    """Images + labels from a RecordIO pack (reference ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record)

    def __getitem__(self, idx):
        from .... import recordio, image
        raw = self._record[idx]
        header, img_bytes = recordio.unpack(raw)
        img = image.imdecode(img_bytes, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """folder/label_name/*.jpg layout (reference ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = {".jpg", ".jpeg", ".png", ".bmp"}
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if os.path.splitext(fname)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from .... import image
        fname, label = self.items[idx]
        img = image.imread(fname, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class DecodedImageRecordDataset(Dataset):
    """Decode-aware RecordIO dataset (ISSUE 7): ``(CHW float32 image,
    float32 label)`` samples with the full ImageRecordIter augmentation
    config — crop/mirror/normalize resolved at decode time from a
    per-INDEX RNG seed, so sample ``i`` is the same bytes no matter who
    decodes it.  That determinism is what lets ``DataLoader`` route this
    dataset through the multi-core shared-memory decode pool
    (io/pipeline.py) when ``num_workers > 0``: pooled batches are
    bit-identical to ``num_workers=0`` in-process loading.

    ``part_index``/``num_parts`` shard the record set for distributed
    loaders (the ImageRecordIter sharding contract).
    """

    def __init__(self, filename, data_shape, path_imgidx=None,
                 rand_crop=False, rand_mirror=False, mean=(0.0, 0.0, 0.0),
                 std=(1.0, 1.0, 1.0), resize=-1, part_index=0, num_parts=1,
                 seed=0):
        from .... import config, recordio
        idx_path = path_imgidx or os.path.splitext(filename)[0] + ".idx"
        if not os.path.exists(idx_path):
            raise MXNetError(
                f"DecodedImageRecordDataset requires an index file "
                f"({idx_path}); create it with tools/im2rec.py")
        self._rec = recordio.MXIndexedRecordIO(idx_path, filename, "r")
        self._keys = list(self._rec.keys)[part_index::num_parts]
        self._seed = int(seed)
        self._cfg = {
            "rec_path": filename,
            "data_shape": tuple(data_shape),
            "resize": resize,
            "rand_crop": bool(rand_crop),
            "rand_mirror": bool(rand_mirror),
            "mean": _np.asarray(mean, _np.float32),
            "std": _np.asarray(std, _np.float32),
            "native": bool(config.get_int("MXNET_USE_NATIVE", 1)),
        }

    def __len__(self):
        return len(self._keys)

    def set_seed(self, seed):
        """Re-seed the per-index augmentation stream (e.g. per epoch)."""
        self._seed = int(seed)

    def _sample_seed(self, idx):
        from ....io.io import _mix_seed
        return _mix_seed(self._seed, idx)

    def __getitem__(self, idx):
        from ....io.io import _decode_record
        raw = self._rec.read_idx(self._keys[idx])
        img, label = _decode_record(
            raw, self._cfg, _np.random.RandomState(self._sample_seed(idx)))
        return img, label

    def _decode_plan(self):
        """The DataLoader decode-pool protocol: (reader, cfg, keys,
        per-index seed fn)."""
        return self._rec, self._cfg, self._keys, self._sample_seed
