"""gluon.data.vision.transforms (reference gluon/data/vision/transforms.py):
Compose, Cast, ToTensor, Normalize, Resize, CenterCrop, RandomResizedCrop,
RandomFlipLeftRight/TopBottom, RandomBrightness/Contrast/Saturation/Hue/
ColorJitter, RandomLighting."""

from __future__ import annotations

import random as _pyrandom

import numpy as _np

from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential
from .... import ndarray as nd

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomHue", "RandomColorJitter", "RandomLighting", "RandomGray"]


class Compose(Sequential):
    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] → CHW float32 [0,1]."""

    def hybrid_forward(self, F, x):
        x = F.cast(x, dtype="float32") / 255.0
        if x.ndim == 3:
            return x.transpose((2, 0, 1))
        return x.transpose((0, 3, 1, 2))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        mean = _np.asarray(self._mean, dtype=_np.float32).reshape(-1, 1, 1)
        std = _np.asarray(self._std, dtype=_np.float32).reshape(-1, 1, 1)
        return (x - nd.array(mean, ctx=x.ctx)) / nd.array(std, ctx=x.ctx)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        from .... import image
        if isinstance(self._size, int):
            if self._keep:
                h, w = x.shape[0], x.shape[1]
                if w < h:
                    size = (self._size, int(h * self._size / w))
                else:
                    size = (int(w * self._size / h), self._size)
            else:
                size = (self._size, self._size)
        else:
            size = self._size
        return image.imresize(x, size[0], size[1], self._interpolation)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._interpolation = interpolation

    def forward(self, x):
        from .... import image
        return image.center_crop(x, self._size, self._interpolation)[0]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio
        self._interpolation = interpolation

    def forward(self, x):
        from .... import image
        return image.random_size_crop(x, self._size, self._scale, self._ratio,
                                      self._interpolation)[0]


class _RandomFlip(Block):
    axis = 1

    def forward(self, x):
        if _pyrandom.random() < 0.5:
            return x.flip(axis=self.axis)
        return x


class RandomFlipLeftRight(_RandomFlip):
    axis = 1


class RandomFlipTopBottom(_RandomFlip):
    axis = 0


class _RandomJitter(Block):
    def __init__(self, amount):
        super().__init__()
        self._amount = amount

    def _factor(self):
        return 1.0 + _pyrandom.uniform(-self._amount, self._amount)


class RandomBrightness(_RandomJitter):
    def forward(self, x):
        return (x * self._factor()).clip(0, 255 if x.dtype == _np.uint8
                                         else 1e30)


class RandomContrast(_RandomJitter):
    def forward(self, x):
        f = self._factor()
        mean = x.astype("float32").mean()
        return (x.astype("float32") * f + mean * (1 - f))


class RandomSaturation(_RandomJitter):
    def forward(self, x):
        f = self._factor()
        coef = nd.array(_np.array([0.299, 0.587, 0.114],
                                  dtype=_np.float32).reshape(1, 1, 3))
        gray = (x.astype("float32") * coef).sum(axis=2, keepdims=True)
        return x.astype("float32") * f + gray * (1 - f)


class RandomHue(_RandomJitter):
    def forward(self, x):
        # simplified hue rotation in YIQ space (reference uses the same trick)
        f = _pyrandom.uniform(-self._amount, self._amount)
        u, w = _np.cos(f * _np.pi), _np.sin(f * _np.pi)
        t_yiq = _np.array([[0.299, 0.587, 0.114], [0.596, -0.274, -0.321],
                           [0.211, -0.523, 0.311]], dtype=_np.float32)
        t_rgb = _np.array([[1, 0.956, 0.621], [1, -0.272, -0.647],
                           [1, -1.107, 1.705]], dtype=_np.float32)
        rot = _np.array([[1, 0, 0], [0, u, -w], [0, w, u]], dtype=_np.float32)
        m = t_rgb.dot(rot).dot(t_yiq).T
        return x.astype("float32").dot(nd.array(m))


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        ts = list(self._ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            x = t(x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA noise."""
    _eigval = _np.array([55.46, 4.794, 1.148], dtype=_np.float32)
    _eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], dtype=_np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        a = _np.random.normal(0, self._alpha, size=(3,)).astype(_np.float32)
        rgb = (self._eigvec * a * self._eigval).sum(axis=1)
        return x.astype("float32") + nd.array(rgb)


class RandomGray(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if _pyrandom.random() < self._p:
            coef = nd.array(_np.array([0.299, 0.587, 0.114],
                                      dtype=_np.float32).reshape(1, 1, 3))
            gray = (x.astype("float32") * coef).sum(axis=2, keepdims=True)
            return gray.tile((1, 1, 3))
        return x
