"""gluon.data.DataLoader (reference gluon/data/dataloader.py, P8).

The reference forks multiprocessing workers that return batches through
POSIX-shared-memory NDArrays (Context kCPUShared).  TPU-native rebuild: the
worker pool is a standard multiprocessing pool returning numpy batches
(pickled via shared mmap when large); the final host→device transfer is one
``jax.device_put`` per batch, which PJRT pipelines asynchronously — the role
pinned memory + copy streams play in the reference.  ``num_workers=0`` is the
synchronous in-process path (default, and the sensible choice on the 1-core
sandbox).
"""

from __future__ import annotations

import multiprocessing as mp

import numpy as _np

from ... import config
from ... import ndarray as nd
from ... import resilience as _res
from ... import telemetry as _tel
from ...telemetry import stepclock as _sclock
from ...ndarray.ndarray import NDArray
from ...resilience import chaos as _chaos
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]

_M_BATCH_SECONDS = _tel.histogram(
    "mxnet_dataloader_batch_seconds",
    "Host latency to materialize one batch (fetch + batchify).")
_M_BATCHES = _tel.counter(
    "mxnet_dataloader_batches_total", "Batches yielded by DataLoader.")
_M_QUEUE_DEPTH = _tel.gauge(
    "mxnet_dataloader_queue_depth",
    "Outstanding prefetched batches in the worker pool.")


def default_batchify_fn(data):
    """Stack samples into a batch (reference default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp
        return NDArray._from_data(jnp.stack([d._data for d in data]))
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(x)) for x in zip(*data))
    arr = _np.asarray(data)
    return nd.array(arr)


default_mp_batchify_fn = default_batchify_fn


def _as_numpy_sample(sample):
    if isinstance(sample, NDArray):
        return sample.asnumpy()
    if isinstance(sample, (tuple, list)):
        return tuple(_as_numpy_sample(s) for s in sample)
    return sample


_worker_dataset = None


def _worker_init(dataset):
    global _worker_dataset
    _worker_dataset = dataset


def _worker_fn(samples):
    batch = [_as_numpy_sample(_worker_dataset[i]) for i in samples]
    if isinstance(batch[0], tuple):
        return tuple(_np.asarray(x) for x in zip(*batch))
    return _np.asarray(batch)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120):  # noqa: ARG002
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler "
                                 "is not given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                        last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._prefetch = max(0, prefetch or 2 * self._num_workers)
        # bounded pool-failure budget before degrading to single-process
        # loading (ISSUE 3 graceful degradation)
        self._max_pool_failures = config.get_int("MXNET_DATALOADER_RETRIES", 2)
        self._pool = None
        self._io_pipeline = None
        self._io_pipeline_slots = 0
        self._io_pipeline_busy = False
        self._decode_pool_failures = 0
        # decode-aware datasets (ISSUE 7: vision.DecodedImageRecordDataset)
        # publish a decode plan; with workers and the default batchify, the
        # loader skips the generic pickle pool entirely and drives the
        # shared-memory decode pipeline instead — bit-identical batches,
        # zero image bytes through pickle
        self._use_decode_pool = (
            self._num_workers > 0
            and batchify_fn is None
            and hasattr(dataset, "_decode_plan")
            and config.get_int("MXNET_IO_POOL", 1) != 0)
        if self._num_workers > 0 and not self._use_decode_pool:
            self._pool = mp.get_context("fork").Pool(
                self._num_workers, initializer=_worker_init,
                initargs=(dataset,))

    def _materialize(self, batch_idx, hit_chaos=True):
        """In-process fetch + batchify of one batch (the synchronous path
        and the pool-failure fallback; chaos site ``dataloader.fetch``).
        Fallback continuations pass ``hit_chaos=False``: they ARE the
        fault handler, and re-entering the armed site inside the handler
        would turn an injected transient into an epoch crash."""
        with _tel.span("dataloader.batch", "data",
                       samples=len(batch_idx)) as sp:
            if hit_chaos and _chaos._ACTIVE:
                _chaos.hit("dataloader.fetch")
            batch = self._batchify_fn(
                [self._dataset[i] for i in batch_idx])
        if sp is not _tel.NULL_SPAN:
            _M_BATCHES.inc()
            _M_BATCH_SECONDS.observe(sp.duration_s)
            # input-wait for the StepClock: this fetch blocks the step
            # that consumes the batch (folded in at its begin_step)
            _sclock.STEP_CLOCK.note("data_wait", sp.duration_s)
        return batch

    def __iter__(self):
        if self._use_decode_pool:
            yield from self._iter_decode_pool()
            return
        if self._pool is None:
            for batch_idx in self._batch_sampler:
                yield self._materialize(batch_idx)
            return
        yield from self._iter_pool()

    def _iter_decode_pool(self):
        """Shared-memory decode-pipeline path (ISSUE 7): the epoch's batch
        plan goes to io.pipeline.PooledDecodePipeline — worker processes
        decode records straight into shared slabs ahead of the consumer,
        with the same in-process-refetch → permanent-single-process
        degradation ladder as the generic pool (a fault here — chaos at
        ``dataloader.fetch``, a pipeline error past ITS OWN internal
        ladder — finishes the epoch via ``_materialize``, which decodes
        the same per-index seeds, so the batch bytes don't change; past
        ``MXNET_DATALOADER_RETRIES`` episodes the loader abandons the
        pipeline for good).  The pipeline (and its worker pool) persists
        across epochs."""
        import warnings
        from ...io.pipeline import PooledDecodePipeline
        if self._io_pipeline_busy:
            # nested/concurrent iteration: the pipeline is ONE ordered
            # stream — a second epoch through it would drain the active
            # generator's schedule and steal its batches.  Decode this
            # iteration in-process instead (same per-index seeds → same
            # bytes), matching the synchronous path's semantics.
            for b in self._batch_sampler:
                yield self._materialize(list(b))
            return
        self._io_pipeline_busy = True
        try:
            rec, cfg, keys, seed_fn = self._dataset._decode_plan()
            batches = [list(b) for b in self._batch_sampler]
            if not batches:
                return
            slots = max(len(b) for b in batches)
            if self._io_pipeline is None or self._io_pipeline_slots < slots:
                if self._io_pipeline is not None:
                    self._io_pipeline.close()
                self._io_pipeline = PooledDecodePipeline(
                    rec, cfg, workers=self._num_workers, slots=slots)
                self._io_pipeline_slots = slots
            pipe = self._io_pipeline
            pipe.drain()
            pipe.begin([([keys[i] for i in b], [seed_fn(i) for i in b])
                        for b in batches])
            for bi in range(len(batches)):
                try:
                    with _tel.span("dataloader.batch", "data") as sp:
                        if _chaos._ACTIVE:
                            _chaos.hit("dataloader.fetch")
                        # private arrays, materialized off-slab by the
                        # pipeline's assembler thread — safe for nd.array
                        # to zero-copy-alias
                        imgs, labels = pipe.next_batch()
                        out = (nd.array(imgs), nd.array(labels))
                except Exception as exc:  # noqa: BLE001 — ladder, not crash
                    self._decode_pool_failures += 1
                    pipe.drain()
                    permanent = \
                        self._decode_pool_failures > self._max_pool_failures
                    if permanent:
                        self._use_decode_pool = False
                        self._shutdown_pool()
                    warnings.warn(
                        f"DataLoader decode pipeline failed ({exc!r}); "
                        + ("degrading permanently to single-process loading"
                           if permanent else
                           "finishing this epoch in-process"), stacklevel=2)
                    for bj in range(bi, len(batches)):
                        yield self._materialize(batches[bj], hit_chaos=False)
                    return
                if sp is not _tel.NULL_SPAN:
                    _M_BATCHES.inc()
                    _M_BATCH_SECONDS.observe(sp.duration_s)
                    _sclock.STEP_CLOCK.note("data_wait", sp.duration_s)
                yield out
        finally:
            self._io_pipeline_busy = False

    def _iter_pool(self):
        """Async pool path with bounded prefetch.  A crashed or hung
        worker must not hang training: each ``get`` is bounded by
        ``timeout``, a failed batch is refetched in-process (the dataset
        lives in this process too), and after MXNET_DATALOADER_RETRIES
        failures the pool is abandoned for single-process loading."""
        import warnings
        results = []  # (batch_idx, AsyncResult)
        it = iter(self._batch_sampler)
        failures = 0

        def issue():
            try:
                idx = next(it)
            except StopIteration:
                return False
            results.append((idx, self._pool.apply_async(_worker_fn, (idx,))))
            return True

        for _ in range(self._prefetch):
            if not issue():
                break
        while results:
            idx, r = results.pop(0)
            issue()
            if _tel.enabled():
                _M_QUEUE_DEPTH.set(len(results))
            with _tel.span("dataloader.batch", "data",
                           queue_depth=len(results)) as sp:
                try:
                    if _chaos._ACTIVE:
                        _chaos.hit("dataloader.fetch")
                    batch = r.get(self._timeout)
                    if isinstance(batch, tuple):
                        out = tuple(nd.array(b) for b in batch)
                    else:
                        out = nd.array(batch)
                except Exception as exc:  # noqa: BLE001 — degrade, don't hang
                    failures += 1
                    _res.record_fallback()
                    warnings.warn(
                        f"DataLoader worker batch failed ({exc!r}); "
                        "refetched in-process", stacklevel=2)
                    out = self._batchify_fn(
                        [self._dataset[i] for i in idx])
            if sp is not _tel.NULL_SPAN:
                _M_BATCHES.inc()
                _M_BATCH_SECONDS.observe(sp.duration_s)
                _sclock.STEP_CLOCK.note("data_wait", sp.duration_s)
            yield out
            if failures and failures >= self._max_pool_failures \
                    and self._pool is not None:
                # the pool is unreliable — degrade permanently to
                # single-process loading for the rest of this loader's life
                warnings.warn(
                    f"DataLoader worker pool failed {failures} times; "
                    "degrading to single-process loading", stacklevel=2)
                pending = [i for i, _ in results]
                results.clear()
                self._shutdown_pool()
                for batch_idx in pending:
                    yield self._materialize(batch_idx, hit_chaos=False)
                for batch_idx in it:
                    yield self._materialize(batch_idx, hit_chaos=False)
                return

    def _shutdown_pool(self):
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.terminate()
        pipe, self._io_pipeline = self._io_pipeline, None
        if pipe is not None:
            pipe.close()

    def __len__(self):
        return len(self._batch_sampler)

    def __del__(self):
        self._shutdown_pool()
