"""gluon.Block / HybridBlock — the user-facing model API.

Rebuild of python/mxnet/gluon/block.py (P6) + src/imperative/cached_op.cc
(N5).  API parity: ``Block`` (child auto-registration, ``collect_params``,
name scopes), ``HybridBlock`` (``hybrid_forward(F, x, **params)``,
``hybridize()``, ``export()``, ``infer_shape`` via deferred param init),
``SymbolBlock``-style import is handled by ``model.load_checkpoint``.

TPU-native CachedOp: instead of capturing an nnvm subgraph and re-executing it
through the C++ engine with a static memory plan, ``hybridize()`` traces the
block's Python forward into a ``jax.jit``-compiled function of
``(params..., inputs..., rng_key)``, cached per (input shapes/dtypes,
train-flag).  The whole block then dispatches as ONE registry op — a single
fused XLA computation (the reference's static_alloc/static_shape/bulking all
collapse into what XLA does natively), and autograd records one vjp for the
whole block.  Mutated auxiliary states (BatchNorm running stats) are detected
at trace time and threaded out as extra outputs, then written back to their
slots after each call — preserving FMutateInputs semantics functionally.
"""

from __future__ import annotations

import re
import threading

import numpy as _np

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock", "CachedOp"]

_naming = threading.local()


def _prefix_counter(hint):
    if not hasattr(_naming, "counts"):
        _naming.counts = {}
    n = _naming.counts.get(hint, 0)
    _naming.counts[hint] = n + 1
    return f"{hint}{n}_"


class _BlockScope:
    """Name scope machinery (reference block.py :: _BlockScope)."""
    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _prefix_counter(hint)
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            hint_count = current._counter.get(hint, 0)
            current._counter[hint] = hint_count + 1
            prefix = f"{hint}{hint_count}_"
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *exc):
        if self._block._empty_prefix:
            return False
        _BlockScope._current.value = self._old
        return False


class Block:
    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params,
                                                        self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pat = re.compile(select)
            ret.update({k: v for k, v in self.params.items() if pat.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init=init, ctx=ctx, verbose=verbose,
                                         force_reinit=force_reinit)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self.params.values():
            p.cast(dtype)

    def save_parameters(self, filename, deduplicate=False):  # noqa: ARG002
        params = self._collect_params_with_prefix()
        nd.save(filename, {k: v.data() for k, v in params.items()})

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + k: v for k, v in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):  # noqa: ARG002
        loaded = nd.load(filename, ctx=ctx)
        params = self._collect_params_with_prefix()
        full_dict = self.collect_params()
        for name, p in params.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif p.name in loaded:
                p.set_data(loaded[p.name])
            elif not allow_missing:
                raise MXNetError(f"Parameter {name} missing in {filename}")
        if not ignore_extra:
            known = set(params) | {p.name for p in params.values()} \
                | set(full_dict.keys())
            extra = [k for k in loaded if k not in known]
            if extra:
                raise MXNetError(f"{filename} has extra parameters {extra}")

    # alias pair used across reference versions
    save_params = save_parameters
    load_params = load_parameters

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a per-layer summary (reference HybridBlock.summary)."""
        rows = []

        def hook_factory(blk, bname):
            def hook(b, inp, out):
                shape = out.shape if isinstance(out, NDArray) else \
                    [o.shape for o in out if isinstance(o, NDArray)]
                n_params = sum(int(_np.prod(p.shape))
                               for p in b._reg_params.values()
                               if p.shape is not None)
                rows.append((bname, type(b).__name__, shape, n_params))
            return hook

        handles = []
        def attach(b, bname):
            h = hook_factory(b, bname)
            b._forward_hooks.append(h)
            handles.append((b, h))
            for n, c in b._children.items():
                attach(c, f"{bname}.{n}" if bname else n)
        attach(self, "")
        try:
            self(*inputs)
        finally:
            for b, h in handles:
                b._forward_hooks.remove(h)
        print(f"{'Layer':<40}{'Output Shape':<24}{'Params':<12}")
        print("-" * 76)
        total = 0
        for bname, cls, shape, n in rows:
            print(f"{bname + ' (' + cls + ')':<40}{str(shape):<24}{n:<12}")
            total += n
        print("-" * 76)
        print(f"Total params (incl. shared): {total}")

    def __repr__(self):
        lines = []
        for name, child in self._children.items():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {child_repr}")
        body = "\n".join(lines)
        return f"{type(self).__name__}(\n{body}\n)" if body \
            else f"{type(self).__name__}()"


class CachedOp:
    """The hybridize() execution object (reference src/imperative/cached_op.cc).

    Holds per-(shape,dtype,train) jitted callables of
    ``f(rng_key, *param_arrays, *input_arrays) -> (outputs..., mutated_aux...)``.
    """

    def __init__(self, block, static_alloc=False, static_shape=False,
                 inline_limit=2, flags=None):  # noqa: ARG002 - XLA handles both
        self.block = block
        self._cache = {}
        self._donate = bool(static_alloc)  # donation ≈ static_alloc reuse

    def _trace(self, params, inputs, train_mode, kwargs):
        import jax
        from .. import autograd, random as _rnd

        param_list = list(params)
        n_p = len(param_list)
        mutated_idx = []  # filled during trace
        key_uses = [0]    # whether the block consumes RNG (dropout etc.)

        from ..ndarray.ndarray import swap_slot_values

        def raw(key, *arrays):
            p_arr = arrays[:n_p]
            i_arr = arrays[n_p:]
            with swap_slot_values(zip((p._data for p in param_list),
                                      p_arr)) as saved:
                in_nds = [NDArray._from_data(a) for a in i_arr]
                scope = _rnd.trace_key_scope(key)
                with autograd._scope(recording=False, training=train_mode), \
                        scope:
                    out = self.block.hybrid_forward_dispatch(*in_nds, **kwargs)
                key_uses[0] = max(key_uses[0], scope.uses)
                outs = [out] if isinstance(out, NDArray) else list(out)
                out_arrays = [o._data for o in outs]
                mutated_idx.clear()
                mut_arrays = []
                for i, (p, (slot, old)) in enumerate(zip(param_list, saved)):
                    if slot.value is not old and slot.value is not p_arr[i]:
                        mutated_idx.append(i)
                        mut_arrays.append(slot.value)
                all_out = tuple(out_arrays) + tuple(mut_arrays)
                # single output must be a leaf, not a 1-tuple, so the captured
                # vjp accepts a bare cotangent
                return all_out if len(all_out) > 1 else all_out[0]

        # graftcheck: ignore[GC02] — deliberate CachedOp protocol: raw
        # reads self.block/params at trace time, and the per-shape cache is
        # keyed on (shapes, dtypes, train_mode) + cleared on dispatch-epoch
        # bumps (amp toggles), so no stale capture survives; mutated_idx /
        # key_uses are trace-time out-params, not runtime state
        jitted = jax.jit(raw)
        # abstract trace now so mutated_idx and the output count are known
        key0 = jax.random.PRNGKey(0)
        out_shapes = jax.eval_shape(raw, key0,
                                    *[p.data()._data for p in param_list],
                                    *inputs)
        n_total = len(out_shapes) if isinstance(out_shapes, (tuple, list)) \
            else 1
        return jitted, list(mutated_idx), n_total, bool(key_uses[0])

    def __call__(self, param_list, input_nds, train_mode, kwargs):
        from ..ops import registry as _reg
        from .. import random as _rnd

        # select the param replica co-located with the inputs (multi-ctx DP);
        # the trace itself is ctx-agnostic (same shapes) and shared
        ctx = next((a.ctx for a in input_nds), None)
        in_arrays = [a._data for a in input_nds]
        # amp on/off bumps the dispatch epoch ⇒ drop stale traces (their
        # cast decisions are baked in; keeping them would leak executables)
        if getattr(self, "_cache_epoch", None) != _reg.dispatch_epoch():
            self._cache.clear()
            self._cache_epoch = _reg.dispatch_epoch()
        key = tuple((tuple(a.shape), str(a.dtype)) for a in in_arrays) \
            + (train_mode, tuple(sorted(kwargs.items())))
        entry = self._cache.get(key)
        if entry is None:
            entry = self._trace(param_list, in_arrays, train_mode, kwargs)
            self._cache[key] = entry
        jitted, mutated_idx, n_total, uses_rng = entry
        n_p = len(param_list)
        n_mut = len(mutated_idx)
        n_out = n_total - n_mut

        if uses_rng:
            def fn(*arrays, _key=None):
                return jitted(_key, *arrays)
        else:
            import jax
            _key0 = jax.random.PRNGKey(0)

            def fn(*arrays):
                return jitted(_key0, *arrays)

        op = _reg.Op(f"CachedOp_{self.block.name}", fn,
                     num_outputs=n_total,
                     visible_outputs=n_out,
                     mutate_inputs=tuple(
                         (n_out + j, mutated_idx[j]) for j in range(n_mut)),
                     wrap_key="_key" if uses_rng else None, jit=False)
        p_nds = [p.data(ctx) for p in param_list]
        res = _reg.invoke(op, p_nds + input_nds, {})
        return res


class HybridBlock(Block):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None
        self._cached_op_args = {}
        self._flags = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        self._active = active
        self._cached_op = None
        self._cached_op_args = dict(static_alloc=static_alloc,
                                    static_shape=static_shape, **kwargs)
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def cast(self, dtype):
        self._cached_op = None
        super().cast(dtype)

    def _clear_cached_op(self):
        self._cached_op = None

    def infer_shape(self, *args):
        """Resolve deferred-init params from concrete input shapes (the nnvm
        InferShape role; here each layer's infer_param_shapes rule)."""
        self.hybrid_forward_dispatch(*args)

    def infer_param_shapes(self, args):
        """Layer-specific deferred-shape rule; layers with deferred params
        override (Dense/Conv/BatchNorm...)."""
        pending = [p.name for p in self._reg_params.values()
                   if p._data is None and p._deferred_init is not None]
        if pending:
            raise DeferredInitializationError(
                f"{type(self).__name__} cannot infer shapes for deferred "
                f"parameters {pending}; initialize them explicitly")

    def hybrid_forward_dispatch(self, *args, **kwargs):
        """Call user hybrid_forward with F + param kwargs (imperative F).
        Params are selected by the input's context so multi-ctx data
        parallelism uses the replica living with the data (reference
        passes ctx through DataParallel executor groups)."""
        pending = [p for p in self._reg_params.values()
                   if p._data is None and p._deferred_init is not None]
        if pending:
            self.infer_param_shapes(args)
            for p in pending:
                p._finish_deferred_init()
        ctx = next((a.ctx for a in args if isinstance(a, NDArray)), None)
        params = {name: p.data(ctx) for name, p in self._reg_params.items()}
        return self.hybrid_forward(nd, *args, **params, **kwargs)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def forward(self, *args, **kwargs):
        if self._active:
            try:
                return self._call_cached_op(*args, **kwargs)
            except DeferredInitializationError:
                # first call with deferred params: one imperative pass
                # resolves them layer-by-layer, then the cached op compiles
                self.hybrid_forward_dispatch(*args, **kwargs)
                return self._call_cached_op(*args, **kwargs)
        return self.hybrid_forward_dispatch(*args, **kwargs)

    def _call_cached_op(self, *args, **kwargs):
        from .. import autograd
        if self._cached_op is None:
            self._cached_op = CachedOp(self, **{
                k: v for k, v in self._cached_op_args.items()
                if k in ("static_alloc", "static_shape", "inline_limit")})
        params = list(self.collect_params().values())
        # every param must be concrete before tracing
        for p in params:
            if p._data is None:
                raise DeferredInitializationError(
                    f"Parameter {p.name} not yet initialized for CachedOp")
        input_nds = [a for a in args if isinstance(a, NDArray)]
        return self._cached_op(params, input_nds, autograd.is_training(),
                               kwargs)

    def export(self, path, epoch=0):
        """Serialize compiled graph + params (reference HybridBlock.export →
        symbol json + .params pair; here real StableHLO text + .params).

        The block must have been hybridized and called at least once so a
        compiled cache entry exists (same precondition as the reference)."""
        import jax
        params = list(self.collect_params().values())
        fname_params = f"{path}-{epoch:04d}.params"
        nd.save(fname_params, {p.name: p.data() for p in params})
        if not (self._cached_op and self._cached_op._cache):
            raise MXNetError(
                "export() requires hybridize() and at least one forward call "
                "(reference raises on un-hybridized export)")
        cache_key, entry = next(iter(self._cached_op._cache.items()))
        jitted = entry[0]
        # cache key = ((shape, dtype_str) per input..., train_mode, kwargs)
        in_specs = [jax.ShapeDtypeStruct(s, _np.dtype(d))
                    for s, d in cache_key[:-2]]
        lowered = jitted.lower(jax.random.PRNGKey(0),
                               *[p.data()._data for p in params], *in_specs)
        hlo = lowered.as_text()
        with open(f"{path}-symbol.txt", "w") as f:
            f.write(hlo)
        return fname_params


class SymbolBlock(HybridBlock):
    """Run a symbolic graph (or traced callable) as a Gluon block.

    Two construction paths, mirroring the reference:
     - ``SymbolBlock(callable)`` wraps a live traced function;
     - ``SymbolBlock.imports(symbol_file, input_names, param_file)`` loads
       the json+params interchange pair written by ``Symbol.save`` /
       ``model.save_checkpoint`` (reference
       gluon/block.py :: SymbolBlock.imports) and executes it through the
       graph executor — the "train anywhere, serve elsewhere" round trip.
    """

    def __init__(self, outputs_fn=None, params=None, prefix=None):
        super().__init__(prefix=prefix, params=params)
        self._fn = outputs_fn
        self._symbol = None
        self._input_names = None
        self._imported_params = {}
        self._sb_executor = None
        self._sb_shapes = None

    @classmethod
    def imports(cls, symbol_file, input_names, param_file=None, ctx=None):
        """Load symbol json (+ optional .params) for inference.

        ``param_file`` entries may be 'arg:NAME'/'aux:NAME'-prefixed
        (Module/save_checkpoint convention) or flat names (Gluon
        save_parameters convention)."""
        from .. import symbol as _sym
        from .. import ndarray as _ndm
        sym = _sym.load(symbol_file) if isinstance(symbol_file, str) \
            else symbol_file
        if isinstance(input_names, str):
            input_names = [input_names]
        blk = cls()
        blk._symbol = sym
        blk._input_names = list(input_names)
        blk._sb_ctx = ctx
        if param_file:
            loaded = _ndm.load(param_file)
            blk._imported_params = {
                (k.split(":", 1)[1] if k.startswith(("arg:", "aux:"))
                 else k): v
                for k, v in loaded.items()}
        return blk

    def forward(self, *args, **kwargs):
        if self._symbol is None:
            return super().forward(*args, **kwargs)
        from ..context import current_context
        if kwargs:
            raise MXNetError(
                "SymbolBlock takes inputs positionally in input_names "
                f"order {self._input_names} (got kwargs {list(kwargs)})")
        if len(args) != len(self._input_names):
            raise MXNetError(
                f"SymbolBlock expects {len(self._input_names)} inputs "
                f"{self._input_names}, got {len(args)}")
        ctx = self._sb_ctx or current_context()
        # inputs land on the bind ctx like the imported params do — feeding
        # a cpu buffer into a tpu-bound executor is the classic device bug
        ins = [(a if isinstance(a, NDArray) else nd.array(a))
               .as_in_context(ctx) for a in args]
        shapes = tuple(tuple(a.shape) for a in ins)
        if self._sb_executor is None or self._sb_shapes != shapes:
            shape_kw = dict(zip(self._input_names, shapes))
            try:
                ex = self._symbol.simple_bind(ctx, grad_req="null",
                                              **shape_kw)
            except MXNetError as e:
                params = set(self._imported_params)
                unbound = [a for a in self._symbol.list_arguments()
                           if a not in params
                           and a not in self._input_names]
                raise MXNetError(
                    f"SymbolBlock: could not bind — unbound inputs "
                    f"{unbound} are neither in input_names nor in the "
                    "param file. For a training checkpoint with a loss "
                    "head (e.g. SoftmaxOutput's *_label), either list the "
                    "label in input_names or strip the head first: "
                    "sym.get_internals()['<name>_output'] "
                    "(reference SymbolBlock.imports contract)") from e
            for name in list(ex.arg_dict):
                if name in self._imported_params:
                    # .params files load on cpu; land them on the bind ctx
                    ex.arg_dict[name] = \
                        self._imported_params[name].as_in_context(ctx)
            for name in list(ex.aux_dict):
                if name in self._imported_params:
                    ex.aux_dict[name] = \
                        self._imported_params[name].as_in_context(ctx)
            self._sb_executor, self._sb_shapes = ex, shapes
        self._sb_executor.forward(
            is_train=False, **dict(zip(self._input_names, ins)))
        outs = self._sb_executor.outputs
        return outs[0] if len(outs) == 1 else outs

    def hybrid_forward(self, F, *args, **params):  # noqa: ARG002
        return self._fn(*args, **params)
