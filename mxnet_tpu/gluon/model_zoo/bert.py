"""BERT-style transformer encoder — the flagship perf model (BASELINE:
BERT-base pretrain ≥45% MFU north star).

Reference anchors: the attention fast path mirrors
src/operator/contrib/transformer.cc (`_contrib_interleaved_matmul_selfatt_qk`
/ `_valatt`, `_contrib_div_sqrt_dim`) which GluonNLP's BERT uses on GPU; the
block structure follows GluonNLP bert.py (external repo — the reference keeps
no transformer model in-tree, SURVEY §5.7).

TPU-native notes:
 - time-major (L, B, C) through the encoder cells so the fused interleaved
   attention ops keep the reference layout contract;
 - ``apply_tp_shardings(model, axis='tp')`` annotates the megatron split
   (qkv/ffn-in column-parallel, proj/ffn-out row-parallel) via
   ``Parameter.sharding`` hints consumed by parallel.TrainStep — GSPMD then
   partitions the matmuls over the mesh's 'tp' axis;
 - flash attention (pallas) plugs in underneath the same ops when available
   (ops/contrib.py).
"""

from __future__ import annotations

import numpy as _np

from ..block import HybridBlock
from ..nn import Dense, Dropout, Embedding, LayerNorm

__all__ = ["BERTEncoderCell", "BERTEncoder", "BERTModel", "bert_model",
           "apply_tp_shardings"]


class BERTEncoderCell(HybridBlock):
    """One post-norm transformer encoder block over the fused attention ops."""

    def __init__(self, units=768, hidden_size=3072, num_heads=12,
                 dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._num_heads = num_heads
        with self.name_scope():
            self.attn_qkv = Dense(3 * units, flatten=False, in_units=units,
                                  prefix="attn_qkv_")
            self.attn_proj = Dense(units, flatten=False, in_units=units,
                                   prefix="attn_proj_")
            self.ffn_1 = Dense(hidden_size, flatten=False, in_units=units,
                               prefix="ffn1_")
            self.ffn_2 = Dense(units, flatten=False, in_units=hidden_size,
                               prefix="ffn2_")
            self.layer_norm_att = LayerNorm(in_channels=units, prefix="ln1_")
            self.layer_norm_ffn = LayerNorm(in_channels=units, prefix="ln2_")
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x, valid_length=None):
        # x: (L, B, C) time-major (reference transformer.cc layout contract).
        # valid_length (B,): padding positions neither attend nor are
        # attended to (GluonNLP BERT masking contract).
        qkv = self.attn_qkv(x)
        # valid_length None = every position valid, a STATIC fact: the
        # flash kernel compiles without mask passes (padded batches pass
        # real lengths and get the segment-masked kernels)
        ctx_vec = F.contrib.masked_selfatt(qkv, valid_length,
                                           heads=self._num_heads)
        out = self.layer_norm_att(x + self.drop(self.attn_proj(ctx_vec)))
        h = self.ffn_2(F.gelu(self.ffn_1(out)))
        return self.layer_norm_ffn(out + self.drop(h))


class BERTEncoder(HybridBlock):
    def __init__(self, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.cells = []
        with self.name_scope():
            for i in range(num_layers):
                cell = BERTEncoderCell(units, hidden_size, num_heads, dropout,
                                       prefix=f"layer{i}_")
                self.register_child(cell, f"layer{i}")
                self.cells.append(cell)

    def hybrid_forward(self, F, x, valid_length=None):
        for cell in self.cells:
            x = cell(x) if valid_length is None else cell(x, valid_length)
        return x


class BERTModel(HybridBlock):
    """Embeddings + encoder + pooler + MLM decoder.

    ``forward(tokens)`` or ``forward(tokens, valid_length)`` (batch-major
    (B, L) int tokens; valid_length (B,) sequence lengths — padded positions
    are masked out of attention, the GluonNLP BERT contract) returns
    ``(sequence_output (B, L, C), pooled (B, C), mlm_logits (B, L, V))``.
    """

    def __init__(self, vocab_size=30522, num_layers=12, units=768,
                 hidden_size=3072, num_heads=12, max_length=512,
                 dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_length = max_length
        with self.name_scope():
            self.word_embed = Embedding(vocab_size, units, prefix="word_")
            self.position_weight = self.params.get(
                "position_weight", shape=(max_length, units), init=None)
            self.embed_norm = LayerNorm(in_channels=units, prefix="embln_")
            self.embed_drop = Dropout(dropout)
            self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                       num_heads, dropout, prefix="enc_")
            self.pooler = Dense(units, flatten=False, in_units=units,
                                activation="tanh", prefix="pooler_")
            self.decoder = Dense(vocab_size, flatten=False, in_units=units,
                                 prefix="decoder_")

    def hybrid_forward(self, F, tokens, valid_length=None,
                       position_weight=None):
        seq_len = tokens.shape[1]
        x = self.word_embed(tokens)
        pos = F.slice_axis(position_weight, axis=0, begin=0, end=seq_len)
        x = x + F.expand_dims(pos, axis=0)
        x = self.embed_drop(self.embed_norm(x))
        x = F.transpose(x, axes=(1, 0, 2))       # (B,L,C) -> (L,B,C)
        x = self.encoder(x, valid_length) if valid_length is not None \
            else self.encoder(x)
        x = F.transpose(x, axes=(1, 0, 2))       # back to (B,L,C)
        first = F.reshape(F.slice_axis(x, axis=1, begin=0, end=1),
                          shape=(0, -1))
        pooled = self.pooler(first)
        logits = self.decoder(x)
        return x, pooled, logits


_BERT_CONFIGS = {
    # name: (num_layers, units, hidden, heads)
    "bert_12_768_12": (12, 768, 3072, 12),
    "bert_24_1024_16": (24, 1024, 4096, 16),
    "bert_6_512_8": (6, 512, 2048, 8),
    "bert_3_128_2": (3, 128, 512, 2),   # tiny (tests / dryrun)
}


def bert_model(name="bert_12_768_12", vocab_size=30522, max_length=512,
               dropout=0.1, **kwargs):
    if name not in _BERT_CONFIGS:
        raise ValueError(f"unknown BERT config {name!r}; "
                         f"known {sorted(_BERT_CONFIGS)}")
    L, U, H, A = _BERT_CONFIGS[name]
    return BERTModel(vocab_size=vocab_size, num_layers=L, units=U,
                     hidden_size=H, num_heads=A, max_length=max_length,
                     dropout=dropout, **kwargs)


def apply_tp_shardings(model, axis="tp"):
    """Annotate megatron-style tensor-parallel shardings on a BERTModel —
    delegates to the declarative rule pack (mxnet_tpu.sharding
    .bert_rules): attn qkv + ffn_1 column-parallel, attn proj + ffn_2
    row-parallel, word/decoder tables over the vocab dim.  Dense weights
    are (out_features, in_features)."""
    from ... import sharding as _sh
    _sh.apply_rules(model, _sh.bert_rules(tp=axis))
    return model
