"""Transformer-base MT (encoder-decoder) — BASELINE config 3's second half
("GluonNLP: BERT-base pretrain + Transformer-base MT").

Reference anchors: the attention fast paths consume the fused contrib ops
mirroring src/operator/contrib/transformer.cc — self-attention via
``contrib.masked_selfatt`` (interleaved qkv layout) and cross-attention via
``contrib.masked_encdec_att`` (the encdec qk/valatt chain's fused form);
the block structure follows GluonNLP's transformer.py (external repo — the
reference keeps no transformer model in-tree, SURVEY §5.7/§1 L11).

Architecture = Vaswani et al. transformer-base: 6+6 layers, d=512,
ffn=2048, 8 heads, post-norm, sinusoidal positions, shared target
embedding / output projection.  TPU-native notes: time-major (L, B, C)
through the cells (the fused ops' layout contract); the causal decoder
mask is a static fact (no mask tensors); label smoothing lives in
``gluon.loss.LabelSmoothedCELoss``.
"""

from __future__ import annotations

import numpy as _np

from ..block import HybridBlock
from ..nn import Dense, Dropout, LayerNorm

__all__ = ["TransformerEncoderCell", "TransformerDecoderCell",
           "TransformerEncoder", "TransformerDecoder", "TransformerModel",
           "transformer_model", "greedy_decode", "beam_search_decode"]


def _positional_encoding(max_len, units):
    """Sinusoidal position table (transformer-base; no learned table)."""
    pos = _np.arange(max_len)[:, None]
    dim = _np.arange(0, units, 2)[None, :]
    angle = pos / _np.power(10000.0, dim / units)
    enc = _np.zeros((max_len, units), _np.float32)
    enc[:, 0::2] = _np.sin(angle)
    enc[:, 1::2] = _np.cos(angle)
    return enc


class TransformerEncoderCell(HybridBlock):
    """Post-norm encoder block over the fused self-attention op."""

    def __init__(self, units=512, hidden_size=2048, num_heads=8,
                 dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._num_heads = num_heads
        with self.name_scope():
            self.attn_qkv = Dense(3 * units, flatten=False, in_units=units,
                                  prefix="attn_qkv_")
            self.attn_proj = Dense(units, flatten=False, in_units=units,
                                   prefix="attn_proj_")
            self.ffn_1 = Dense(hidden_size, flatten=False, in_units=units,
                               prefix="ffn1_")
            self.ffn_2 = Dense(units, flatten=False, in_units=hidden_size,
                               prefix="ffn2_")
            self.ln_att = LayerNorm(in_channels=units, prefix="ln1_")
            self.ln_ffn = LayerNorm(in_channels=units, prefix="ln2_")
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x, valid_length=None):
        qkv = self.attn_qkv(x)                        # (L, B, 3C)
        ctx = F.contrib.masked_selfatt(qkv, valid_length,
                                       heads=self._num_heads)
        out = self.ln_att(x + self.drop(self.attn_proj(ctx)))
        h = self.ffn_2(F.relu(self.ffn_1(out)))       # base uses ReLU ffn
        return self.ln_ffn(out + self.drop(h))


class TransformerDecoderCell(HybridBlock):
    """Post-norm decoder block: causal self-attention + fused
    cross-attention over the encoder memory."""

    def __init__(self, units=512, hidden_size=2048, num_heads=8,
                 dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._num_heads = num_heads
        with self.name_scope():
            self.attn_qkv = Dense(3 * units, flatten=False, in_units=units,
                                  prefix="self_qkv_")
            self.attn_proj = Dense(units, flatten=False, in_units=units,
                                   prefix="self_proj_")
            self.cross_q = Dense(units, flatten=False, in_units=units,
                                 prefix="cross_q_")
            # one fused [k,v] projection of the memory — the encdec layout
            self.cross_kv = Dense(2 * units, flatten=False, in_units=units,
                                  prefix="cross_kv_")
            self.cross_proj = Dense(units, flatten=False, in_units=units,
                                   prefix="cross_proj_")
            self.ffn_1 = Dense(hidden_size, flatten=False, in_units=units,
                               prefix="ffn1_")
            self.ffn_2 = Dense(units, flatten=False, in_units=hidden_size,
                               prefix="ffn2_")
            self.ln_self = LayerNorm(in_channels=units, prefix="ln1_")
            self.ln_cross = LayerNorm(in_channels=units, prefix="ln2_")
            self.ln_ffn = LayerNorm(in_channels=units, prefix="ln3_")
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x, mem, mem_valid_length=None):
        # x (Lt, B, C) target stream; mem (Ls, B, C) encoder output
        qkv = self.attn_qkv(x)
        ctx = F.contrib.masked_selfatt(qkv, None, heads=self._num_heads,
                                       causal=True)
        out = self.ln_self(x + self.drop(self.attn_proj(ctx)))
        cross = F.contrib.masked_encdec_att(
            self.cross_q(out), self.cross_kv(mem), mem_valid_length,
            heads=self._num_heads)
        out = self.ln_cross(out + self.drop(self.cross_proj(cross)))
        h = self.ffn_2(F.relu(self.ffn_1(out)))
        return self.ln_ffn(out + self.drop(h))


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.cells = []
        with self.name_scope():
            for i in range(num_layers):
                cell = TransformerEncoderCell(units, hidden_size, num_heads,
                                              dropout, prefix=f"layer{i}_")
                self.register_child(cell, f"layer{i}")
                self.cells.append(cell)

    def hybrid_forward(self, F, x, valid_length=None):
        for cell in self.cells:
            x = cell(x) if valid_length is None else cell(x, valid_length)
        return x


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.cells = []
        with self.name_scope():
            for i in range(num_layers):
                cell = TransformerDecoderCell(units, hidden_size, num_heads,
                                              dropout, prefix=f"layer{i}_")
                self.register_child(cell, f"layer{i}")
                self.cells.append(cell)

    def hybrid_forward(self, F, x, mem, mem_valid_length=None):
        for cell in self.cells:
            x = cell(x, mem, mem_valid_length)
        return x


class TransformerModel(HybridBlock):
    """Encoder-decoder MT model.

    ``forward(src_tokens, tgt_tokens[, src_valid_length])`` takes
    batch-major (B, Ls)/(B, Lt) int tokens (tgt already shifted right by
    the caller: BOS-prefixed) and returns (B, Lt, V) next-token logits.
    Source padding is masked via ``src_valid_length`` (B,); target padding
    is the LOSS's job (label smoothing + padding weight), matching the
    GluonNLP training contract.

    The token embedding is ONE (vocab, units) table shared by source,
    target, AND the output softmax projection (the three-way tying of the
    transformer-base recipe), declared model-level the same way bert.py
    declares position_weight so the tie survives hybridize/CachedOp.
    """

    def __init__(self, vocab_size=32768, num_layers=6, units=512,
                 hidden_size=2048, num_heads=8, max_length=1024,
                 dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._vocab = vocab_size
        with self.name_scope():
            self.embed_weight = self.params.get(
                "embed_weight", shape=(vocab_size, units), init=None)
            self.encoder = TransformerEncoder(num_layers, units, hidden_size,
                                              num_heads, dropout,
                                              prefix="enc_")
            self.decoder = TransformerDecoder(num_layers, units, hidden_size,
                                              num_heads, dropout,
                                              prefix="dec_")
            self.drop = Dropout(dropout)
        self._pos = _positional_encoding(max_length, units)

    def _embed(self, F, weight, tokens):
        # gather, scale by sqrt(d), add sinusoids (transformer-base recipe)
        x = F.Embedding(tokens, weight, input_dim=self._vocab,
                        output_dim=self._units) * float(self._units) ** 0.5
        pos = F.array(self._pos[:tokens.shape[1]]).astype(x.dtype)
        x = x + F.expand_dims(pos, axis=0)
        return F.transpose(self.drop(x), axes=(1, 0, 2))   # (L, B, C)

    def _encode_impl(self, F, embed_weight, src_tokens, src_valid_length):
        mem = self._embed(F, embed_weight, src_tokens)
        return self.encoder(mem) if src_valid_length is None \
            else self.encoder(mem, src_valid_length)

    def _decode_impl(self, F, embed_weight, mem, tgt_tokens,
                     src_valid_length):
        y = self._embed(F, embed_weight, tgt_tokens)
        y = self.decoder(y, mem, src_valid_length)
        y = F.transpose(y, axes=(1, 0, 2))                 # (B, Lt, C)
        # tied output projection: logits = y @ embed^T
        logits = F.dot(y.reshape((-1, self._units)), embed_weight,
                       transpose_b=True)
        return logits.reshape((tgt_tokens.shape[0], tgt_tokens.shape[1], -1))

    def hybrid_forward(self, F, src_tokens, tgt_tokens,
                       src_valid_length=None, embed_weight=None):
        mem = self._encode_impl(F, embed_weight, src_tokens,
                                src_valid_length)
        return self._decode_impl(F, embed_weight, mem, tgt_tokens,
                                 src_valid_length)

    def encode(self, src_tokens, src_valid_length=None):
        """Run the encoder ONCE and return its memory (Ls, B, C) — the
        half of ``hybrid_forward`` whose inputs never change during
        autoregressive decode.  Pair with :meth:`decode_from_memory`."""
        from ... import ndarray as F
        return self._encode_impl(F, self.embed_weight.data(), src_tokens,
                                 src_valid_length)

    def decode_from_memory(self, mem, tgt_tokens, src_valid_length=None):
        """Decoder + tied projection over a cached encoder memory:
        identical math (and logits) to ``self(src, tgt, vl)`` when ``mem``
        came from :meth:`encode` on the same source — the decode loops
        call this every step so the encoder runs once per sentence, not
        once per emitted token."""
        from ... import ndarray as F
        return self._decode_impl(F, self.embed_weight.data(), mem,
                                 tgt_tokens, src_valid_length)


_CONFIGS = {
    # name: (layers, units, hidden, heads)
    "transformer_base": (6, 512, 2048, 8),
    "transformer_big": (6, 1024, 4096, 16),
    "transformer_test": (2, 64, 128, 4),     # tiny (unit tests)
}


def transformer_model(name="transformer_base", vocab_size=32768,
                      max_length=1024, dropout=0.1, **kwargs):
    if name not in _CONFIGS:
        raise ValueError(f"unknown transformer config {name!r}; "
                         f"known {sorted(_CONFIGS)}")
    L, U, H, A = _CONFIGS[name]
    return TransformerModel(vocab_size=vocab_size, num_layers=L, units=U,
                            hidden_size=H, num_heads=A,
                            max_length=max_length, dropout=dropout, **kwargs)


def greedy_decode(model, src_tokens, bos_id, eos_id, max_len=64,
                  src_valid_length=None):
    """Greedy autoregressive decode: argmax next token until EOS/max_len.

    The target rides a FIXED (B, max_len) buffer and every step runs the
    same compiled shape — decoder causality makes the PAD tail beyond the
    current position invisible to the positions that matter, so the
    growing-prefix retrace (a fresh XLA compile per emitted token) never
    happens.  The source is encoded ONCE and every step decodes against
    the cached memory; the decoder itself still re-runs the full buffer
    per step (the example/eval path — ``mx.serving`` is the production
    path with a paged k/v cache and O(L) decode).  Returns (B, <=max_len)
    int32 including BOS, stopping early only when EVERY sequence has
    emitted EOS.
    """
    import numpy as np
    from ... import ndarray as mxnd
    B = src_tokens.shape[0]
    # the fixed buffer embeds positions 0..max_len-1 every step, so it
    # must fit the model's position table (the growing-prefix variant
    # only failed if decoding actually REACHED the limit)
    cap = getattr(model, "_pos", None)
    if cap is not None:
        max_len = min(max_len, cap.shape[0])
    buf = np.full((B, max_len), eos_id, np.int32)   # pad tail = EOS id
    buf[:, 0] = bos_id
    done = np.zeros((B,), bool)
    n = 1
    # the source never changes across steps: encode ONCE and decode every
    # step against the cached memory (identical logits to the full call)
    mem = model.encode(src_tokens, src_valid_length)
    for t in range(max_len - 1):
        logits = model.decode_from_memory(mem, mxnd.array(buf),
                                          src_valid_length)
        nxt = np.asarray(logits.asnumpy()[:, t].argmax(-1), np.int32)
        nxt = np.where(done, eos_id, nxt)
        buf[:, t + 1] = nxt
        done |= nxt == eos_id
        n = t + 2
        if done.all():
            break
    return buf[:, :n]


def beam_search_decode(model, src_tokens, bos_id, eos_id, beam_size=4,
                       max_len=64, alpha=0.6, src_valid_length=None):
    """Beam-search decode (the GluonNLP BeamSearchSampler role for MT).

    Length-normalized scores use the GNMT penalty
    ``((5 + len) / 6) ** alpha``; hypotheses that emit EOS move to a
    COMPLETED pool at their normalized score (so a short finished
    hypothesis is never evicted by longer raw-score competitors — the
    BeamSearchScorer contract), and the search stops early once every
    live beam is worse than the pool even with the best possible
    remaining score.  Same fixed-shape discipline as ``greedy_decode``:
    one (B*K, max_len) buffer, one compiled shape per step (causality
    hides the pad tail), the replicated source encoded ONCE up front.
    Host-side numpy picks the beams — the example/eval path; production
    serving (``mx.serving``) jits the loop with paged k/v caches.
    Returns (best (B, <=max_len) int32 incl. BOS, scores (B,)
    length-normalized log-probs).
    """
    import numpy as np
    from ... import ndarray as mxnd
    B = src_tokens.shape[0]
    K = beam_size
    cap = getattr(model, "_pos", None)
    if cap is not None:
        max_len = min(max_len, cap.shape[0])
    src_np = src_tokens.asnumpy() if hasattr(src_tokens, "asnumpy") \
        else np.asarray(src_tokens)
    # each batch row replicated K times: (B*K, Ls), beams vary the target
    src_rep = mxnd.array(np.repeat(src_np, K, axis=0))
    vl_rep = None
    if src_valid_length is not None:
        vl_np = src_valid_length.asnumpy() \
            if hasattr(src_valid_length, "asnumpy") \
            else np.asarray(src_valid_length)
        vl_rep = mxnd.array(np.repeat(vl_np, K, axis=0))

    def penalty(length):
        return ((5.0 + length) / 6.0) ** alpha

    buf = np.full((B, K, max_len), eos_id, np.int32)
    buf[:, :, 0] = bos_id
    scores = np.full((B, K), -np.inf, np.float64)
    scores[:, 0] = 0.0            # beams start identical: keep one live
    # completed pool: per batch row, the best (normalized_score, tokens)
    best_done = [(-np.inf, None)] * B
    n = 1
    # the replicated source is step-invariant: one encoder pass feeds
    # every decode step (and every beam reshuffle — beams share a row's
    # memory by construction)
    mem = model.encode(src_rep, vl_rep)
    for t in range(max_len - 1):
        flat = mxnd.array(buf.reshape(B * K, max_len))
        logits = model.decode_from_memory(mem, flat, vl_rep)
        # slice + log_softmax ON DEVICE (the registered op — one
        # log-softmax implementation in the codebase), then pull only the
        # (B*K, V) step slice over the tunnel
        logp = mxnd.log_softmax(logits[:, t], axis=-1).asnumpy() \
            .astype(np.float64)
        V = logp.shape[-1]
        logp = logp.reshape(B, K, V)
        # EOS continuations COMPLETE a hypothesis: score it normalized
        # into the pool, then exclude EOS from the live expansion
        for b in range(B):
            for k in range(K):
                if not np.isfinite(scores[b, k]):
                    continue
                fin = (scores[b, k] + logp[b, k, eos_id]) / penalty(t + 1)
                if fin > best_done[b][0]:
                    seq = buf[b, k, :t + 2].copy()
                    seq[t + 1] = eos_id
                    best_done[b] = (fin, seq)
        logp[:, :, eos_id] = -np.inf
        cand = scores[:, :, None] + logp            # (B, K, V)
        flat_cand = cand.reshape(B, K * V)
        part = np.argpartition(-flat_cand, K - 1, axis=1)[:, :K]
        part_scores = np.take_along_axis(flat_cand, part, 1)
        order = np.argsort(-part_scores, axis=1)
        top = np.take_along_axis(part, order, 1)     # (B, K) best-first
        new_scores = np.take_along_axis(flat_cand, top, 1)
        beam_idx, tok_idx = top // V, top % V
        buf = np.take_along_axis(
            buf, beam_idx[:, :, None].astype(np.int64), axis=1)
        buf[:, :, t + 1] = tok_idx.astype(np.int32)
        scores = new_scores
        n = t + 2
        # early stop: even a perfect (0 log-prob) continuation cannot
        # beat the completed pool for any row
        bound = scores[:, 0] / penalty(max_len - 1)
        if all(best_done[b][0] >= bound[b] for b in range(B)):
            break
    out = np.full((B, n), eos_id, np.int32)
    final = np.empty((B,), np.float64)
    for b in range(B):
        sc, seq = best_done[b]
        if seq is None:
            # no hypothesis ever finished: fall back to the best live beam
            seq = buf[b, 0, :n]
            sc = scores[b, 0] / penalty(n - 1)
        out[b, :len(seq)] = seq[:n]
        final[b] = sc
    return out, final
