"""Transformer-base MT (encoder-decoder) — BASELINE config 3's second half
("GluonNLP: BERT-base pretrain + Transformer-base MT").

Reference anchors: the attention fast paths consume the fused contrib ops
mirroring src/operator/contrib/transformer.cc — self-attention via
``contrib.masked_selfatt`` (interleaved qkv layout) and cross-attention via
``contrib.masked_encdec_att`` (the encdec qk/valatt chain's fused form);
the block structure follows GluonNLP's transformer.py (external repo — the
reference keeps no transformer model in-tree, SURVEY §5.7/§1 L11).

Architecture = Vaswani et al. transformer-base: 6+6 layers, d=512,
ffn=2048, 8 heads, post-norm, sinusoidal positions, shared target
embedding / output projection.  TPU-native notes: time-major (L, B, C)
through the cells (the fused ops' layout contract); the causal decoder
mask is a static fact (no mask tensors); label smoothing lives in
``gluon.loss.LabelSmoothedCELoss``.
"""

from __future__ import annotations

import numpy as _np

from ..block import HybridBlock
from ..nn import Dense, Dropout, LayerNorm

__all__ = ["TransformerEncoderCell", "TransformerDecoderCell",
           "TransformerEncoder", "TransformerDecoder", "TransformerModel",
           "transformer_model", "greedy_decode"]


def _positional_encoding(max_len, units):
    """Sinusoidal position table (transformer-base; no learned table)."""
    pos = _np.arange(max_len)[:, None]
    dim = _np.arange(0, units, 2)[None, :]
    angle = pos / _np.power(10000.0, dim / units)
    enc = _np.zeros((max_len, units), _np.float32)
    enc[:, 0::2] = _np.sin(angle)
    enc[:, 1::2] = _np.cos(angle)
    return enc


class TransformerEncoderCell(HybridBlock):
    """Post-norm encoder block over the fused self-attention op."""

    def __init__(self, units=512, hidden_size=2048, num_heads=8,
                 dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._num_heads = num_heads
        with self.name_scope():
            self.attn_qkv = Dense(3 * units, flatten=False, in_units=units,
                                  prefix="attn_qkv_")
            self.attn_proj = Dense(units, flatten=False, in_units=units,
                                   prefix="attn_proj_")
            self.ffn_1 = Dense(hidden_size, flatten=False, in_units=units,
                               prefix="ffn1_")
            self.ffn_2 = Dense(units, flatten=False, in_units=hidden_size,
                               prefix="ffn2_")
            self.ln_att = LayerNorm(in_channels=units, prefix="ln1_")
            self.ln_ffn = LayerNorm(in_channels=units, prefix="ln2_")
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x, valid_length=None):
        qkv = self.attn_qkv(x)                        # (L, B, 3C)
        ctx = F.contrib.masked_selfatt(qkv, valid_length,
                                       heads=self._num_heads)
        out = self.ln_att(x + self.drop(self.attn_proj(ctx)))
        h = self.ffn_2(F.relu(self.ffn_1(out)))       # base uses ReLU ffn
        return self.ln_ffn(out + self.drop(h))


class TransformerDecoderCell(HybridBlock):
    """Post-norm decoder block: causal self-attention + fused
    cross-attention over the encoder memory."""

    def __init__(self, units=512, hidden_size=2048, num_heads=8,
                 dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._num_heads = num_heads
        with self.name_scope():
            self.attn_qkv = Dense(3 * units, flatten=False, in_units=units,
                                  prefix="self_qkv_")
            self.attn_proj = Dense(units, flatten=False, in_units=units,
                                   prefix="self_proj_")
            self.cross_q = Dense(units, flatten=False, in_units=units,
                                 prefix="cross_q_")
            # one fused [k,v] projection of the memory — the encdec layout
            self.cross_kv = Dense(2 * units, flatten=False, in_units=units,
                                  prefix="cross_kv_")
            self.cross_proj = Dense(units, flatten=False, in_units=units,
                                   prefix="cross_proj_")
            self.ffn_1 = Dense(hidden_size, flatten=False, in_units=units,
                               prefix="ffn1_")
            self.ffn_2 = Dense(units, flatten=False, in_units=hidden_size,
                               prefix="ffn2_")
            self.ln_self = LayerNorm(in_channels=units, prefix="ln1_")
            self.ln_cross = LayerNorm(in_channels=units, prefix="ln2_")
            self.ln_ffn = LayerNorm(in_channels=units, prefix="ln3_")
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x, mem, mem_valid_length=None):
        # x (Lt, B, C) target stream; mem (Ls, B, C) encoder output
        qkv = self.attn_qkv(x)
        ctx = F.contrib.masked_selfatt(qkv, None, heads=self._num_heads,
                                       causal=True)
        out = self.ln_self(x + self.drop(self.attn_proj(ctx)))
        cross = F.contrib.masked_encdec_att(
            self.cross_q(out), self.cross_kv(mem), mem_valid_length,
            heads=self._num_heads)
        out = self.ln_cross(out + self.drop(self.cross_proj(cross)))
        h = self.ffn_2(F.relu(self.ffn_1(out)))
        return self.ln_ffn(out + self.drop(h))


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.cells = []
        with self.name_scope():
            for i in range(num_layers):
                cell = TransformerEncoderCell(units, hidden_size, num_heads,
                                              dropout, prefix=f"layer{i}_")
                self.register_child(cell, f"layer{i}")
                self.cells.append(cell)

    def hybrid_forward(self, F, x, valid_length=None):
        for cell in self.cells:
            x = cell(x) if valid_length is None else cell(x, valid_length)
        return x


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.cells = []
        with self.name_scope():
            for i in range(num_layers):
                cell = TransformerDecoderCell(units, hidden_size, num_heads,
                                              dropout, prefix=f"layer{i}_")
                self.register_child(cell, f"layer{i}")
                self.cells.append(cell)

    def hybrid_forward(self, F, x, mem, mem_valid_length=None):
        for cell in self.cells:
            x = cell(x, mem, mem_valid_length)
        return x


class TransformerModel(HybridBlock):
    """Encoder-decoder MT model.

    ``forward(src_tokens, tgt_tokens[, src_valid_length])`` takes
    batch-major (B, Ls)/(B, Lt) int tokens (tgt already shifted right by
    the caller: BOS-prefixed) and returns (B, Lt, V) next-token logits.
    Source padding is masked via ``src_valid_length`` (B,); target padding
    is the LOSS's job (label smoothing + padding weight), matching the
    GluonNLP training contract.

    The token embedding is ONE (vocab, units) table shared by source,
    target, AND the output softmax projection (the three-way tying of the
    transformer-base recipe), declared model-level the same way bert.py
    declares position_weight so the tie survives hybridize/CachedOp.
    """

    def __init__(self, vocab_size=32768, num_layers=6, units=512,
                 hidden_size=2048, num_heads=8, max_length=1024,
                 dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._vocab = vocab_size
        with self.name_scope():
            self.embed_weight = self.params.get(
                "embed_weight", shape=(vocab_size, units), init=None)
            self.encoder = TransformerEncoder(num_layers, units, hidden_size,
                                              num_heads, dropout,
                                              prefix="enc_")
            self.decoder = TransformerDecoder(num_layers, units, hidden_size,
                                              num_heads, dropout,
                                              prefix="dec_")
            self.drop = Dropout(dropout)
        self._pos = _positional_encoding(max_length, units)

    def _embed(self, F, weight, tokens):
        # gather, scale by sqrt(d), add sinusoids (transformer-base recipe)
        x = F.Embedding(tokens, weight, input_dim=self._vocab,
                        output_dim=self._units) * float(self._units) ** 0.5
        pos = F.array(self._pos[:tokens.shape[1]]).astype(x.dtype)
        x = x + F.expand_dims(pos, axis=0)
        return F.transpose(self.drop(x), axes=(1, 0, 2))   # (L, B, C)

    def hybrid_forward(self, F, src_tokens, tgt_tokens,
                       src_valid_length=None, embed_weight=None):
        mem = self._embed(F, embed_weight, src_tokens)
        mem = self.encoder(mem) if src_valid_length is None \
            else self.encoder(mem, src_valid_length)
        y = self._embed(F, embed_weight, tgt_tokens)
        y = self.decoder(y, mem, src_valid_length)
        y = F.transpose(y, axes=(1, 0, 2))                 # (B, Lt, C)
        # tied output projection: logits = y @ embed^T
        logits = F.dot(y.reshape((-1, self._units)), embed_weight,
                       transpose_b=True)
        return logits.reshape((tgt_tokens.shape[0], tgt_tokens.shape[1], -1))


_CONFIGS = {
    # name: (layers, units, hidden, heads)
    "transformer_base": (6, 512, 2048, 8),
    "transformer_big": (6, 1024, 4096, 16),
    "transformer_test": (2, 64, 128, 4),     # tiny (unit tests)
}


def transformer_model(name="transformer_base", vocab_size=32768,
                      max_length=1024, dropout=0.1, **kwargs):
    if name not in _CONFIGS:
        raise ValueError(f"unknown transformer config {name!r}; "
                         f"known {sorted(_CONFIGS)}")
    L, U, H, A = _CONFIGS[name]
    return TransformerModel(vocab_size=vocab_size, num_layers=L, units=U,
                            hidden_size=H, num_heads=A,
                            max_length=max_length, dropout=dropout, **kwargs)


def greedy_decode(model, src_tokens, bos_id, eos_id, max_len=64,
                  src_valid_length=None):
    """Greedy autoregressive decode: argmax next token until EOS/max_len.

    The target rides a FIXED (B, max_len) buffer and every step runs the
    same compiled shape — decoder causality makes the PAD tail beyond the
    current position invisible to the positions that matter, so the
    growing-prefix retrace (a fresh XLA compile per emitted token) never
    happens.  O(L^2) total work (re-encodes each step — the example/eval
    path; production serving would cache k/v).  Returns (B, <=max_len)
    int32 including BOS, stopping early only when EVERY sequence has
    emitted EOS.
    """
    import numpy as np
    from ... import ndarray as mxnd
    B = src_tokens.shape[0]
    # the fixed buffer embeds positions 0..max_len-1 every step, so it
    # must fit the model's position table (the growing-prefix variant
    # only failed if decoding actually REACHED the limit)
    cap = getattr(model, "_pos", None)
    if cap is not None:
        max_len = min(max_len, cap.shape[0])
    buf = np.full((B, max_len), eos_id, np.int32)   # pad tail = EOS id
    buf[:, 0] = bos_id
    done = np.zeros((B,), bool)
    n = 1
    for t in range(max_len - 1):
        logits = model(src_tokens, mxnd.array(buf),
                       src_valid_length) if src_valid_length is not None \
            else model(src_tokens, mxnd.array(buf))
        nxt = np.asarray(logits.asnumpy()[:, t].argmax(-1), np.int32)
        nxt = np.where(done, eos_id, nxt)
        buf[:, t + 1] = nxt
        done |= nxt == eos_id
        n = t + 2
        if done.all():
            break
    return buf[:, :n]
