"""Llama-style decoder LLM through the Gluon HybridBlock API — the
BASELINE stretch config 5 ("Llama-3-8B trains via HybridBlock API with
TP/SP/CP shardings").

Architecture (Llama 3 family): pre-RMSNorm decoder blocks, rotary
position embeddings, grouped-query attention (n_kv_heads < n_heads),
SwiGLU MLP, untied LM head, causal masking.  The reference has no LLM
in-tree (SURVEY §5.7 — its transformer support tops out at the fused
single-device attention ops); this model exists to prove the Gluon API
stretches to modern LLM shape + sharding requirements.

Parallelism hooks (consumed by ``parallel.TrainStep`` via
``Parameter.sharding`` GSPMD hints):
 - ``apply_tp_shardings(model)`` — megatron split: qkv + gate/up
   column-parallel, o_proj + down row-parallel, embeddings/LM head over
   the vocab dim.
 - sequence/context parallelism: attention lowers through
   ``contrib.masked_selfatt`` (flash/dense); for a sequence-sharded mesh
   use ``parallel.attention`` (ring attention) with the same q/k/v
   layout — see kernels/ring_attention.py.

Configs: ``llama3_8b`` (the stretch target: 32L/4096/14336/32H/8KV) plus
tiny variants for tests and the multichip dryrun.
"""

from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from ..nn import Dense, Embedding

__all__ = ["LlamaModel", "llama_model", "apply_tp_shardings",
           "LLAMA_CONFIGS"]

# name -> (layers, units, hidden, heads, kv_heads)
LLAMA_CONFIGS = {
    "llama3_8b": (32, 4096, 14336, 32, 8),
    "llama_tiny": (2, 64, 172, 4, 2),        # tests / dryrun
    "llama_small": (4, 256, 688, 8, 4),
}


class RMSNorm(HybridBlock):
    """Root-mean-square norm (no mean subtraction, no bias) — Llama's
    norm; computed in f32 like the reference implementations."""

    def __init__(self, units, eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        self._eps = eps
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(units,),
                                          init="ones")

    def hybrid_forward(self, F, x, weight):
        xf = x.astype("float32")
        var = (xf * xf).mean(axis=-1, keepdims=True)
        out = xf * F.rsqrt(var + self._eps)
        return (out * weight.astype("float32")).astype(x.dtype)


def _rope(F, x, base=500000.0):
    """Rotary embeddings over the last dim; x: (B, H, L, D)."""
    B, H, L, D = x.shape
    half = D // 2
    inv = 1.0 / (base ** (F.arange(0, half).astype("float32") / half))
    pos = F.arange(L).astype("float32")
    ang = pos.reshape((L, 1)) * inv.reshape((1, half))      # (L, half)
    cos = F.cos(ang).reshape((1, 1, L, half)).astype(x.dtype)
    sin = F.sin(ang).reshape((1, 1, L, half)).astype(x.dtype)
    x1 = x[:, :, :, :half]
    x2 = x[:, :, :, half:]
    return F.concat(x1 * cos - x2 * sin, x1 * sin + x2 * cos, dim=-1)


class LlamaBlock(HybridBlock):
    def __init__(self, units, hidden, heads, kv_heads, attn_impl="fused",
                 sp_axis="sp", **kwargs):
        super().__init__(**kwargs)
        if units % heads or heads % kv_heads:
            raise MXNetError("units % heads and heads % kv_heads must be 0")
        if attn_impl not in ("fused", "ring", "ulysses"):
            raise MXNetError(
                f"attn_impl {attn_impl!r}: want fused|ring|ulysses")
        self._units = units
        self._heads = heads
        self._kv = kv_heads
        self._hd = units // heads
        self._attn_impl = attn_impl
        self._sp_axis = sp_axis
        with self.name_scope():
            self.q_proj = Dense(units, flatten=False, use_bias=False,
                                in_units=units, prefix="q_")
            self.k_proj = Dense(self._hd * kv_heads, flatten=False,
                                use_bias=False, in_units=units, prefix="k_")
            self.v_proj = Dense(self._hd * kv_heads, flatten=False,
                                use_bias=False, in_units=units, prefix="v_")
            self.o_proj = Dense(units, flatten=False, use_bias=False,
                                in_units=units, prefix="o_")
            self.gate = Dense(hidden, flatten=False, use_bias=False,
                              in_units=units, prefix="gate_")
            self.up = Dense(hidden, flatten=False, use_bias=False,
                            in_units=units, prefix="up_")
            self.down = Dense(units, flatten=False, use_bias=False,
                              in_units=hidden, prefix="down_")
            self.attn_norm = RMSNorm(units, prefix="attn_norm_")
            self.mlp_norm = RMSNorm(units, prefix="mlp_norm_")

    def hybrid_forward(self, F, x):
        # x: (B, L, C) batch-major (modern-LLM layout)
        B, L, _ = x.shape
        h = self.attn_norm(x)
        q = self.q_proj(h).reshape((B, L, self._heads, self._hd)) \
            .transpose((0, 2, 1, 3))                       # (B, H, L, D)
        k = self.k_proj(h).reshape((B, L, self._kv, self._hd)) \
            .transpose((0, 2, 1, 3))
        v = self.v_proj(h).reshape((B, L, self._kv, self._hd)) \
            .transpose((0, 2, 1, 3))
        q = _rope(F, q)
        k = _rope(F, k)
        if self._attn_impl != "fused":
            # sequence/context parallelism: ring or Ulysses attention over
            # the current mesh's sp axis (falls back to local attention
            # when no mesh is active — same math, so tests run anywhere)
            ctx_vec = F.contrib.sp_att_qkv(
                q, k, v, impl=self._attn_impl, axis=self._sp_axis,
                num_kv_groups=self._heads // self._kv, causal=True)
        else:
            # direct q/k/v entry point: no interleave round-trip; the GQA
            # kv-head broadcast happens inside the op next to the kernel.
            # valid_length=None is the STATIC all-valid fact — the flash
            # kernel compiles without any mask passes (pure causal)
            ctx_vec = F.contrib.masked_att_qkv(
                q, k, v, None, num_kv_groups=self._heads // self._kv,
                causal=True)                                # (B, H, L, D)
        attn = self.o_proj(ctx_vec.transpose((0, 2, 1, 3))
                           .reshape((B, L, self._units)))
        x = x + attn
        h = self.mlp_norm(x)
        mlp = self.down(F.silu(self.gate(h)) * self.up(h))
        return x + mlp


class LlamaModel(HybridBlock):
    def __init__(self, vocab_size=128256, num_layers=2, units=64,
                 hidden=172, heads=4, kv_heads=2, attn_impl="fused",
                 sp_axis="sp", remat=None, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        # activation rematerialization per decoder block (the reference's
        # MXNET_BACKWARD_DO_MIRROR memory/compute trade — SURVEY §5.7);
        # None = read the env flag at construction
        if remat is None:
            from ... import config as _cfg
            remat = bool(_cfg.get_int("MXNET_BACKWARD_DO_MIRROR", 0))
        self._remat = bool(remat)
        with self.name_scope():
            self.embed = Embedding(vocab_size, units, prefix="tok_")
            self.blocks = []
            for i in range(num_layers):
                blk = LlamaBlock(units, hidden, heads, kv_heads,
                                 attn_impl=attn_impl, sp_axis=sp_axis,
                                 prefix=f"layer{i}_")
                self.register_child(blk, f"layer{i}")
                self.blocks.append(blk)
            self.norm = RMSNorm(units, prefix="final_norm_")
            self.lm_head = Dense(vocab_size, flatten=False, use_bias=False,
                                 in_units=units, prefix="lm_head_")

    def hybrid_forward(self, F, tokens):
        # tokens: (B, L) int32 → logits (B, L, vocab)
        from ... import autograd
        x = self.embed(tokens)
        use_remat = self._remat and autograd.is_recording()
        if use_remat:
            from ..utils import remat_call
        for blk in self.blocks:
            x = remat_call(blk, x) if use_remat else blk(x)
        return self.lm_head(self.norm(x))


def llama_model(name="llama_tiny", vocab_size=32000, **kwargs):
    if name not in LLAMA_CONFIGS:
        raise MXNetError(
            f"unknown llama config {name!r}; options {sorted(LLAMA_CONFIGS)}")
    L, U, H, A, KV = LLAMA_CONFIGS[name]
    return LlamaModel(vocab_size=vocab_size, num_layers=L, units=U,
                      hidden=H, heads=A, kv_heads=KV, **kwargs)


def apply_tp_shardings(model, axis="tp"):
    """Megatron tensor-parallel annotation for a LlamaModel — delegates
    to the declarative rule pack (mxnet_tpu.sharding.llama_rules):
    q/k/v + gate/up + lm_head column-parallel, o_proj + down
    row-parallel, the token table over the vocab dim, norms replicated.
    Dense weights are (out_features, in_features)."""
    from ... import sharding as _sh
    _sh.apply_rules(model, _sh.llama_rules(tp=axis))
    return model
