"""YOLOv3 (BASELINE config 2: "GluonCV: ResNet-50 / YOLOv3 on
ImageNet/COCO").

Reference anchors: GluonCV model_zoo/yolo/yolo3.py + darknet.py (external
repo — the reference keeps detection models in GluonCV; SURVEY §1 L11
records the zoo role).  Rebuilt TPU-first:

 - DarkNet-53 backbone (conv-bn-leaky + residual stages) and the 3-scale
   FPN-style neck/heads are plain HybridBlocks — XLA fuses conv+bn+leaky.
 - Anchor/target assignment is a HOST-side numpy pass
   (``YOLOV3TargetGenerator``) producing STATIC-shape dense target
   tensors, so the jitted train step has no data-dependent shapes — the
   TPU analog of GluonCV's prefetched "fake" targets
   (yolo_target.py::YOLOV3PrefetchTargetGenerator).
 - The loss (``YOLOV3Loss``) is sigmoid-BCE on objectness/class/center +
   L2 on log-wh over the dense masks.
 - ``yolo3_decode`` turns head outputs into (cls, score, box) rows with
   ``contrib.box_nms`` — the eval path.
"""

from __future__ import annotations

import numpy as _np

from ..block import HybridBlock
from ..nn import BatchNorm, Conv2D, HybridSequential

__all__ = ["darknet53", "yolo3_darknet53", "YOLOV3", "YOLOV3Loss",
           "YOLOV3TargetGenerator", "yolo3_decode", "DEFAULT_ANCHORS"]

# COCO-tuned anchors (w, h) in input pixels, 3 per output scale,
# large-stride scale first (stride 32, 16, 8) — the GluonCV defaults
DEFAULT_ANCHORS = (
    ((116, 90), (156, 198), (373, 326)),     # stride 32
    ((30, 61), (62, 45), (59, 119)),         # stride 16
    ((10, 13), (16, 30), (33, 23)),          # stride 8
)


def _conv_bn_leaky(channels, kernel, stride=1, padding=None, prefix=""):
    if padding is None:
        padding = kernel // 2
    blk = HybridSequential(prefix=prefix)
    with blk.name_scope():
        blk.add(Conv2D(channels, kernel, strides=stride, padding=padding,
                       use_bias=False))
        blk.add(BatchNorm(epsilon=1e-5, momentum=0.9))
    blk.add(_Leaky())
    return blk


class _Leaky(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, slope=0.1)


class DarknetBasicBlock(HybridBlock):
    """1x1 squeeze + 3x3 expand with residual add (darknet53 unit)."""

    def __init__(self, channels, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.body = HybridSequential()
            self.body.add(_conv_bn_leaky(channels // 2, 1))
            self.body.add(_conv_bn_leaky(channels, 3))

    def hybrid_forward(self, F, x):
        return x + self.body(x)


class Darknet(HybridBlock):
    """DarkNet backbone returning the three detection-scale features
    (strides 8, 16, 32 relative to the input)."""

    def __init__(self, layers=(1, 2, 8, 8, 4),
                 channels=(32, 64, 128, 256, 512, 1024), **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.stem = _conv_bn_leaky(channels[0], 3)
            self.stages = []
            for i, n in enumerate(layers):
                stage = HybridSequential(prefix=f"stage{i}_")
                with stage.name_scope():
                    stage.add(_conv_bn_leaky(channels[i + 1], 3, stride=2))
                    for _ in range(n):
                        stage.add(DarknetBasicBlock(channels[i + 1]))
                self.register_child(stage, f"stage{i}")
                self.stages.append(stage)

    def hybrid_forward(self, F, x):
        x = self.stem(x)
        feats = []
        for stage in self.stages:
            x = stage(x)
            feats.append(x)
        return feats[-3], feats[-2], feats[-1]   # strides 8, 16, 32


def darknet53(**kwargs):
    """The full DarkNet-53 backbone (GluonCV darknet.py)."""
    return Darknet(layers=(1, 2, 8, 8, 4), **kwargs)


class _YoloDetBlock(HybridBlock):
    """5-conv transition producing the scale's route (for the lateral
    branch) and tip (for the prediction head)."""

    def __init__(self, channels, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.body = HybridSequential()
            for i in range(2):
                self.body.add(_conv_bn_leaky(channels, 1))
                self.body.add(_conv_bn_leaky(channels * 2, 3))
            self.body.add(_conv_bn_leaky(channels, 1))
            self.tip = _conv_bn_leaky(channels * 2, 3)

    def hybrid_forward(self, F, x):
        route = self.body(x)
        return route, self.tip(route)


class YOLOV3(HybridBlock):
    """YOLOv3 detector: backbone -> 3 detection scales -> per-anchor
    raw predictions.

    ``forward(x)`` returns a list of 3 tensors, one per scale
    (stride 32 first), each (B, H*W*A, 5+C) raw (pre-sigmoid) —
    [tx, ty, tw, th, obj, cls...] in the grid parameterization.  Use
    ``YOLOV3Loss`` for training and ``yolo3_decode`` for boxes.
    """

    def __init__(self, backbone=None, classes=80, anchors=DEFAULT_ANCHORS,
                 channels=(512, 256, 128), **kwargs):
        super().__init__(**kwargs)
        self._classes = classes
        self._num_anchors = len(anchors[0])
        self.anchors = anchors
        with self.name_scope():
            self.backbone = backbone if backbone is not None else darknet53()
            self.det_blocks = []
            self.laterals = []
            self.heads = []
            out_ch = self._num_anchors * (5 + classes)
            for i, ch in enumerate(channels):
                blk = _YoloDetBlock(ch, prefix=f"det{i}_")
                self.register_child(blk, f"det{i}")
                self.det_blocks.append(blk)
                head = Conv2D(out_ch, 1, prefix=f"head{i}_")
                self.register_child(head, f"head{i}")
                self.heads.append(head)
                if i < len(channels) - 1:
                    lat = _conv_bn_leaky(channels[i + 1], 1,
                                         prefix=f"lat{i}_")
                    self.register_child(lat, f"lat{i}")
                    self.laterals.append(lat)

    def hybrid_forward(self, F, x):
        b = x.shape[0]
        c8, c16, c32 = self.backbone(x)
        feats = [c32, c16, c8]               # large stride first
        outputs = []
        route = None
        for i, blk in enumerate(self.det_blocks):
            f = feats[i]
            if route is not None:
                up = F.UpSampling(self.laterals[i - 1](route), scale=2,
                                  sample_type="nearest")
                f = F.concat(up, f, dim=1)
            route, tip = blk(f)
            raw = self.heads[i](tip)          # (B, A*(5+C), H, W)
            raw = F.transpose(raw, axes=(0, 2, 3, 1))
            outputs.append(raw.reshape((b, -1, 5 + self._classes)))
        return outputs


def yolo3_darknet53(classes=80, **kwargs):
    """GluonCV ``yolo3_darknet53_coco`` analog (randomly initialized)."""
    return YOLOV3(backbone=darknet53(), classes=classes, **kwargs)


class YOLOV3TargetGenerator:
    """Host-side dense target assignment (numpy) — one call per batch.

    For each gt box the best-IoU anchor (across all scales) is assigned:
    that grid cell's [tx, ty, tw, th, obj=1, one-hot cls] targets are set.
    Anchors whose DECODED prediction would overlap any gt above
    ``ignore_iou`` are excluded from the negative-objectness loss via the
    returned mask (the YOLOv3 ignore rule, applied here statically from
    anchor priors — GluonCV computes it dynamically from predictions; the
    static form keeps the train step shape-stable).

    Returns per scale: obj_t (B,N,1), center_t (B,N,2), scale_t (B,N,2),
    cls_t (B,N,C), pos_mask (B,N,1), neg_mask (B,N,1).
    """

    def __init__(self, classes, anchors=DEFAULT_ANCHORS, strides=(32, 16, 8),
                 input_size=416, ignore_iou=0.5):
        self.classes = classes
        self.anchors = anchors
        self.strides = strides
        self.size = input_size
        self.ignore_iou = ignore_iou

    def _grids(self):
        return [self.size // s for s in self.strides]

    def __call__(self, labels):
        """labels: (B, M, 5) [cls, x0, y0, x1, y1] normalized 0..1,
        -1-padded rows (ImageDetIter contract)."""
        B = labels.shape[0]
        C = self.classes
        grids = self._grids()
        A = len(self.anchors[0])
        out = []
        for g in grids:
            n = g * g * A
            out.append([_np.zeros((B, n, 1), _np.float32),
                        _np.zeros((B, n, 2), _np.float32),
                        _np.zeros((B, n, 2), _np.float32),
                        _np.zeros((B, n, C), _np.float32),
                        _np.zeros((B, n, 1), _np.float32),
                        _np.ones((B, n, 1), _np.float32)])
        flat_anchors = _np.array(
            [a for scale in self.anchors for a in scale], _np.float32)
        for b in range(B):
            for row in labels[b]:
                cls = int(row[0])
                if cls < 0:
                    continue
                x0, y0, x1, y1 = row[1:5] * self.size
                w, h = max(x1 - x0, 1e-3), max(y1 - y0, 1e-3)
                cx, cy = (x0 + x1) / 2, (y0 + y1) / 2
                # best anchor by shape IoU (centered overlap)
                inter = _np.minimum(flat_anchors[:, 0], w) * \
                    _np.minimum(flat_anchors[:, 1], h)
                union = flat_anchors[:, 0] * flat_anchors[:, 1] + w * h \
                    - inter
                ious = inter / union
                best = int(ious.argmax())
                si, ai = divmod(best, A)
                g = grids[si]
                stride = self.strides[si]
                gx, gy = min(int(cx / stride), g - 1), \
                    min(int(cy / stride), g - 1)
                idx = (gy * g + gx) * A + ai
                obj, ctr, scl, clst, pos, neg = out[si]
                obj[b, idx, 0] = 1.0
                ctr[b, idx] = (cx / stride - gx, cy / stride - gy)
                aw, ah = self.anchors[si][ai]
                scl[b, idx] = (_np.log(w / aw), _np.log(h / ah))
                clst[b, idx, cls] = 1.0
                pos[b, idx, 0] = 1.0
                neg[b, idx, 0] = 0.0
                # the static ignore rule: other anchors in cells the gt
                # covers whose prior IoU clears the threshold drop out of
                # the negative loss
                for sj in range(len(grids)):
                    gj = grids[sj]
                    sx0 = max(int(x0 / self.strides[sj]), 0)
                    sx1 = min(int(x1 / self.strides[sj]), gj - 1)
                    sy0 = max(int(y0 / self.strides[sj]), 0)
                    sy1 = min(int(y1 / self.strides[sj]), gj - 1)
                    for aj in range(A):
                        if ious[sj * A + aj] < self.ignore_iou:
                            continue
                        for yy in range(sy0, sy1 + 1):
                            for xx in range(sx0, sx1 + 1):
                                out[sj][5][b, (yy * gj + xx) * A + aj, 0] \
                                    = 0.0
        return out


class YOLOV3Loss:
    """Dense YOLOv3 loss over the generator's static targets: sigmoid-BCE
    objectness (pos + unignored neg) + BCE center + L2 log-wh + BCE class
    (GluonCV yolo3 loss composition)."""

    def __init__(self, obj_weight=1.0, center_weight=2.0, scale_weight=2.0,
                 cls_weight=1.0):
        self.w = (obj_weight, center_weight, scale_weight, cls_weight)

    def __call__(self, F, preds, targets):
        wo, wc, ws, wk = self.w
        total = None
        for raw, (obj_t, ctr_t, scl_t, cls_t, pos, neg) in \
                zip(preds, targets):
            tx_ty = F.slice_axis(raw, axis=-1, begin=0, end=2)
            tw_th = F.slice_axis(raw, axis=-1, begin=2, end=4)
            obj = F.slice_axis(raw, axis=-1, begin=4, end=5)
            cls = F.slice_axis(raw, axis=-1, begin=5, end=None)

            def bce(logit, target, mask):
                per = F.relu(logit) - logit * target + \
                    F.log(1 + F.exp(-F.abs(logit)))
                return (per * mask).sum()

            n_pos = F.maximum(pos.sum(), F.ones_like(pos.sum()))
            l_obj = (bce(obj, obj_t, pos) + bce(obj, obj_t, neg)) / n_pos
            l_ctr = bce(tx_ty, ctr_t, pos) / n_pos
            l_scl = ((tw_th - scl_t) ** 2 * pos).sum() / n_pos
            l_cls = bce(cls, cls_t, pos) / n_pos
            part = wo * l_obj + wc * l_ctr + ws * l_scl + wk * l_cls
            total = part if total is None else total + part
        return total


def yolo3_decode(preds, anchors=DEFAULT_ANCHORS, strides=(32, 16, 8),
                 input_size=416, conf_thresh=0.1, nms_thresh=0.45,
                 topk=100):
    """Decode raw head outputs to (B, topk, 6) [cls, score, x0, y0, x1, y1]
    rows (normalized 0..1), NMS-filtered via contrib.box_nms — the eval
    path (GluonCV's decode lives inside yolo3.py's inference branch)."""
    import numpy as np
    from ... import ndarray as nd
    rows = []
    for raw, sc_anchors, stride in zip(preds, anchors, strides):
        p = raw.asnumpy() if hasattr(raw, "asnumpy") else np.asarray(raw)
        B, N, E = p.shape
        A = len(sc_anchors)
        g = input_size // stride
        xy = 1 / (1 + np.exp(-p[..., 0:2]))
        wh = p[..., 2:4]
        obj = 1 / (1 + np.exp(-p[..., 4:5]))
        cls = 1 / (1 + np.exp(-p[..., 5:]))
        grid = np.stack(np.meshgrid(np.arange(g), np.arange(g)), -1) \
            .reshape(-1, 1, 2)                      # (g*g, 1, 2) [x, y]
        anc = np.asarray(sc_anchors, np.float32).reshape(1, A, 2)
        cxy = (xy.reshape(B, -1, A, 2) + grid) * stride
        pwh = np.exp(np.clip(wh.reshape(B, -1, A, 2), -8, 8)) * anc
        score = (obj * cls).reshape(B, -1, A, cls.shape[-1])
        cid = score.argmax(-1)
        sc = score.max(-1)
        x0y0 = (cxy - pwh / 2) / input_size
        x1y1 = (cxy + pwh / 2) / input_size
        det = np.concatenate(
            [cid[..., None].astype(np.float32), sc[..., None],
             x0y0, x1y1], -1).reshape(B, -1, 6)
        rows.append(det)
    allrows = np.concatenate(rows, axis=1)
    out = nd.contrib.box_nms(nd.array(allrows), overlap_thresh=nms_thresh,
                             valid_thresh=conf_thresh, topk=topk,
                             coord_start=2, score_index=1, id_index=0)
    return out.asnumpy()[:, :topk]
