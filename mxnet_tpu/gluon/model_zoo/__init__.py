"""gluon.model_zoo (reference python/mxnet/gluon/model_zoo, P9).

``vision`` mirrors the reference's CNN zoo; ``bert`` is the GluonNLP-style
transformer family the BASELINE north-star configs train (the reference keeps
BERT in the external GluonNLP repo — here it ships in-tree because it is the
flagship perf model).
"""

from . import bert  # noqa: F401


def __getattr__(name):
    import importlib
    if name in ("vision", "llama", "transformer", "yolo"):
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def get_model(name, **kwargs):
    """Reference model_zoo.get_model factory."""
    from . import vision
    return vision.get_model(name, **kwargs)
