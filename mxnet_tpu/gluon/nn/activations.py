"""gluon.nn activation layers (reference gluon/nn/activations.py)."""

from __future__ import annotations

from ..block import HybridBlock

__all__ = ["LeakyReLU", "PReLU", "ELU", "SELU", "Swish", "GELU", "SiLU"]


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return f"LeakyReLU({self._alpha})"


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as _init
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(1,),
                init=alpha_initializer or _init.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    """GELU activation — exact erf form by default; ``approximate=True``
    (or MXNET_GELU_TANH=1 at construction) selects the tanh
    approximation.  The choice is resolved HERE, not at trace time, so
    it rides the op's attr set into the jit cache key."""

    def __init__(self, approximate=None, **kwargs):
        super().__init__(**kwargs)
        if approximate is None:
            from ... import config
            approximate = bool(config.get_int("MXNET_GELU_TANH", 0))
        self._approximate = bool(approximate)

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu",
                           approximate=self._approximate)

    def __repr__(self):
        return f"GELU(approximate={self._approximate})"


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


SiLU = Swish
