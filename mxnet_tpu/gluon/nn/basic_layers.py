"""gluon.nn basic layers (reference gluon/nn/basic_layers.py, P7):
Sequential/HybridSequential, Dense, Dropout, BatchNorm, InstanceNorm,
LayerNorm, GroupNorm, Embedding, Flatten, Lambda/HybridLambda."""

from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from ..block import Block, HybridBlock
from ..parameter import DeferredInitializationError

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "GroupNorm", "Embedding", "Flatten",
           "Lambda", "HybridLambda"]


class Sequential(Block):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers[key])
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn(
                "All children of this Sequential layer are HybridBlocks. "
                "Consider using HybridSequential for the compiled fast path.",
                stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x, *args):  # noqa: ARG002
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers[key])
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """reference gluon/nn/basic_layers.py :: Dense → FullyConnected op."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype=_np.float32, weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._units = units
            self._flatten = flatten
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            self.act = Activation(activation, prefix=activation + "_") \
                if activation is not None else None
            if self.act is not None:
                self.register_child(self.act, "act")

    def infer_param_shapes(self, args):
        x = args[0]
        in_units = int(_np.prod(x.shape[1:])) if self._flatten \
            else x.shape[-1]
        self.weight.shape_mismatch_update((self._units, in_units))

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               flatten=self._flatten, no_bias=bias is None)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape and len(shape) > 1 else None} -> "
                f"{self._units}, "
                f"{'linear' if self.act is None else self.act._act_type})")


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate <= 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True, differentiable=scale)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True, differentiable=center)
        self.running_mean = self.params.get(
            "running_mean", grad_req="null", shape=(in_channels,),
            init=running_mean_initializer, allow_deferred_init=True,
            differentiable=False)
        self.running_var = self.params.get(
            "running_var", grad_req="null", shape=(in_channels,),
            init=running_variance_initializer, allow_deferred_init=True,
            differentiable=False)

    def infer_param_shapes(self, args):
        c = args[0].shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape_mismatch_update((c,))

    def cast(self, dtype):
        if _np.dtype(dtype).itemsize < 4:
            dtype = _np.float32  # keep BN stats in fp32 (reference behavior)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           eps=self._epsilon, momentum=self._momentum,
                           fix_gamma=not self._scale,
                           use_global_stats=self._use_global_stats,
                           axis=self._axis)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def infer_param_shapes(self, args):
        c = args[0].shape[self._axis]
        self.gamma.shape_mismatch_update((c,))
        self.beta.shape_mismatch_update((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)
        x = x.swapaxes(1, self._axis)
        return F.InstanceNorm(x, gamma, beta,
                              eps=self._epsilon).swapaxes(1, self._axis)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def infer_param_shapes(self, args):
        c = args[0].shape[self._axis]
        self.gamma.shape_mismatch_update((c,))
        self.beta.shape_mismatch_update((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def infer_param_shapes(self, args):
        c = args[0].shape[1]
        self.gamma.shape_mismatch_update((c,))
        self.beta.shape_mismatch_update((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype=_np.float32,
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer,
            grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as _nd
            function = getattr(_nd, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as _nd
            function = getattr(_nd, function)
        self._func = function

    def hybrid_forward(self, F, *args):  # noqa: ARG002
        return self._func(*args)


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type or "activation"

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"
