"""gluon.rnn fused layers (reference python/mxnet/gluon/rnn/rnn_layer.py).

``RNN``/``LSTM``/``GRU`` run the whole multi-layer stack through the fused
``RNN`` op (ops/nn.py — the reference's cuDNN-packed kernel, here a
lax.scan over time so the stack is ONE XLA computation regardless of
sequence length).  Parameters are held individually per
(layer, direction, i2h/h2h) exactly like the reference — names
``{l|r}{k}_{i2h|h2h}_{weight|bias}`` — and packed into the flat cuDNN-order
vector at forward time (pack order: all weights layer-major then all
biases; see ops/nn.py :: _unpack_rnn_params).
"""

from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from . import rnn_cell

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"invalid layout {layout!r}; use TNC or NTC")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size

        with self.name_scope():
            for i in range(num_layers):
                for j in ("l", "r")[:self._dir]:
                    in_sz = ni if i == 0 else nh * self._dir
                    setattr(self, f"{j}{i}_i2h_weight", self.params.get(
                        f"{j}{i}_i2h_weight", shape=(ng * nh, in_sz),
                        init=i2h_weight_initializer,
                        allow_deferred_init=True))
                    setattr(self, f"{j}{i}_h2h_weight", self.params.get(
                        f"{j}{i}_h2h_weight", shape=(ng * nh, nh),
                        init=h2h_weight_initializer,
                        allow_deferred_init=True))
                    setattr(self, f"{j}{i}_i2h_bias", self.params.get(
                        f"{j}{i}_i2h_bias", shape=(ng * nh,),
                        init=i2h_bias_initializer, allow_deferred_init=True))
                    setattr(self, f"{j}{i}_h2h_bias", self.params.get(
                        f"{j}{i}_h2h_bias", shape=(ng * nh,),
                        init=h2h_bias_initializer, allow_deferred_init=True))

    def __repr__(self):
        mapping = f"{self._input_size or None} -> {self._hidden_size}"
        if self._dir == 2:
            mapping += " (bidirectional)"
        return (f"{type(self).__name__}({mapping}, {self._layout}, "
                f"num_layers={self._num_layers})")

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def _param_order(self):
        names = []
        for i in range(self._num_layers):
            for j in ("l", "r")[:self._dir]:
                names.append(f"{j}{i}_i2h_weight")
                names.append(f"{j}{i}_h2h_weight")
        for i in range(self._num_layers):
            for j in ("l", "r")[:self._dir]:
                names.append(f"{j}{i}_i2h_bias")
                names.append(f"{j}{i}_h2h_bias")
        return names

    def infer_param_shapes(self, args):
        x = args[0]
        in_sz = x.shape[-1]
        ng, nh = self._gates, self._hidden_size
        for j in ("l", "r")[:self._dir]:
            getattr(self, f"{j}0_i2h_weight").shape_mismatch_update(
                (ng * nh, in_sz))

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd
        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            states.append(func(info["shape"], **kwargs))
        return states

    def forward(self, inputs, states=None):
        skip_states = states is None
        if skip_states:
            batch = inputs.shape[self._layout.find("N")]
            states = self.begin_state(batch, ctx=inputs.ctx,
                                      dtype=inputs.dtype)
        if not isinstance(states, (list, tuple)):
            states = [states]
        out = super().forward(inputs, *states)
        if isinstance(out, (list, tuple)):
            output, out_states = out[0], list(out[1:])
        else:
            output, out_states = out, []
        if skip_states:
            return output
        return output, out_states

    def hybrid_forward(self, F, inputs, *states, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        flat = F.concat(*[params[n].reshape((-1,))
                          for n in self._param_order()], dim=0)
        res = F.RNN(inputs, flat, *states,
                    state_size=self._hidden_size,
                    num_layers=self._num_layers, mode=self._mode,
                    bidirectional=self._dir == 2, p=self._dropout,
                    state_outputs=True)
        if isinstance(res, (list, tuple)):
            output, out_states = res[0], list(res[1:])
        else:
            output, out_states = res, []
        if self._layout == "NTC":
            output = F.swapaxes(output, dim1=0, dim2=1)
        return tuple([output] + out_states)

    def _unfuse(self):
        """Equivalent stack of cells (reference _RNNLayer._unfuse)."""
        get_cell = {
            "rnn_relu": lambda **kw: rnn_cell.RNNCell(
                self._hidden_size, activation="relu", **kw),
            "rnn_tanh": lambda **kw: rnn_cell.RNNCell(
                self._hidden_size, activation="tanh", **kw),
            "lstm": lambda **kw: rnn_cell.LSTMCell(self._hidden_size, **kw),
            "gru": lambda **kw: rnn_cell.GRUCell(self._hidden_size, **kw),
        }[self._mode]
        stack = rnn_cell.HybridSequentialRNNCell(prefix=self.prefix)
        with stack.name_scope():
            ni = self._input_size
            for i in range(self._num_layers):
                if self._dir == 2:
                    stack.add(rnn_cell.BidirectionalCell(
                        get_cell(prefix=f"l{i}_", input_size=ni),
                        get_cell(prefix=f"r{i}_", input_size=ni)))
                else:
                    stack.add(get_cell(prefix=f"l{i}_", input_size=ni))
                if self._dropout > 0 and i != self._num_layers - 1:
                    stack.add(rnn_cell.DropoutCell(self._dropout))
                ni = self._hidden_size * self._dir
        return stack


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (relu or tanh) — reference gluon.rnn.RNN."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM — reference gluon.rnn.LSTM."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU — reference gluon.rnn.GRU."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
