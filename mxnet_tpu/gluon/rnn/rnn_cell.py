"""gluon.rnn cell zoo (reference python/mxnet/gluon/rnn/rnn_cell.py, P7).

Single-step recurrent cells + ``unroll``.  Gate math matches the fused
``RNN`` op (ops/nn.py :: _cell_step — reference src/operator/rnn-inl.h gate
order): LSTM gates [i, f, g, o]; GRU gates [r, z, n] with
``n = tanh(i2h_n + r * h2h_n)``; biases split i2h/h2h like cuDNN.

TPU note: ``unroll`` builds a static python loop — under ``hybridize()``
the whole unrolled graph compiles to one XLA program, which XLA then
software-pipelines; for long sequences prefer the fused ``rnn_layer``
classes (lax.scan keeps compile time O(1) in sequence length).
"""

from __future__ import annotations

from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ZoneoutCell", "ResidualCell", "BidirectionalCell",
           "ModifierCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    """Normalize unroll inputs: returns (list_or_tensor, axis, batch)."""
    from ... import ndarray as nd
    assert layout in ("NTC", "TNC"), f"invalid layout {layout}"
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        if length is not None and len(inputs) != length:
            raise MXNetError(f"unroll length {length} != inputs {len(inputs)}")
        seq = list(inputs)
        batch = seq[0].shape[0]
        if merge:
            stacked = nd.stack(*seq, axis=axis)
            return stacked, axis, batch
        return seq, axis, batch
    # single tensor
    batch = inputs.shape[batch_axis]
    if length is not None and inputs.shape[axis] != length:
        raise MXNetError(
            f"unroll length {length} != inputs.shape[{axis}] {inputs.shape[axis]}")
    if merge is False:
        n = inputs.shape[axis]
        seq = [s.squeeze(axis=axis) for s in nd.split(
            inputs, num_outputs=n, axis=axis, squeeze_axis=False)] \
            if n > 1 else [inputs.squeeze(axis=axis)]
        return seq, axis, batch
    return inputs, axis, batch


class RecurrentCell(Block):
    """Abstract single-step cell (reference RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        """Reset the step counter used for state-name generation."""
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states: list of zeros (or ``func``) per state_info row."""
        assert not self._modified, \
            "After applying a modifier cell, call begin_state on the base cell"
        from ... import ndarray as nd
        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = tuple(batch_size if s == 0 else s
                          for s in info["shape"])
            info = {k: v for k, v in info.items() if k != "shape"}
            info.update(kwargs)
            states.append(func(shape, **info) if "shape" not in info
                          else func(**info))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Run the cell over ``length`` steps (reference unroll contract).

        Returns (outputs, states); outputs is a single stacked tensor when
        ``merge_outputs`` is True (or None with tensor input), else a list.
        """
        from ... import ndarray as nd
        self.reset()
        seq, axis, batch = _format_sequence(length, inputs, layout, False)
        length = len(seq)
        states = begin_state if begin_state is not None \
            else self.begin_state(batch, ctx=seq[0].ctx, dtype=seq[0].dtype)
        outputs = []
        all_states = []
        for i in range(length):
            out, states = self(seq[i], states)
            outputs.append(out)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            # mask steps beyond each sample's valid length; final states are
            # the states at the last VALID step (reference SequenceLast role)
            steps = nd.arange(length, ctx=seq[0].ctx)
            vl = valid_length.astype("float32")
            picked = []
            for s_idx in range(len(states)):
                stacked = nd.stack(*[s[s_idx] for s in all_states], axis=0)
                idx = (vl - 1).astype("int32")
                picked.append(_pick_batchwise(stacked, idx))
            states = picked
            mask = (steps.reshape((1, -1)) <
                    vl.reshape((-1, 1))).astype(seq[0].dtype)
            outputs = [o * mask[:, i:i + 1] for i, o in enumerate(outputs)]
        if merge_outputs is None:
            merge_outputs = not isinstance(inputs, (list, tuple))
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

def _pick_batchwise(stacked, idx):
    """stacked (T, N, H), idx (N,) → (N, H) picking per-sample step."""
    from ... import ndarray as nd
    T, N = stacked.shape[0], stacked.shape[1]
    flat = stacked.swapaxes(0, 1).reshape((N * T,) + stacked.shape[2:])
    base = nd.arange(N, ctx=stacked.ctx).astype("int32") * T
    return nd.take(flat, base + idx, axis=0)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """Cells whose step is hybridizable."""

    def __init__(self, prefix=None, params=None):
        RecurrentCell.__init__(self, prefix=prefix, params=params)

    def forward(self, inputs, states):
        self._counter += 1
        if isinstance(states, (list, tuple)):
            flat = list(states)
        else:
            flat = [states]
        res = HybridBlock.forward(self, inputs, *flat)
        return res

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class _BaseGatedCell(HybridRecurrentCell):
    """Shared param plumbing for RNN/LSTM/GRU cells."""

    _num_gates = 1

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        ng = self._num_gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(ng * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(ng * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ng * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ng * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def infer_param_shapes(self, args):
        x = args[0]
        self.i2h_weight.shape_mismatch_update(
            (self._num_gates * self._hidden_size, x.shape[-1]))

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def __repr__(self):
        shape = self.i2h_weight.shape
        in_sz = shape[1] if shape and len(shape) > 1 else None
        return f"{type(self).__name__}({in_sz} -> {self._hidden_size})"


class RNNCell(_BaseGatedCell):
    """Elman RNN cell: h' = act(W_i x + b_i + W_h h + b_h)."""

    _num_gates = 1

    def __init__(self, hidden_size, activation="tanh", **kwargs):
        super().__init__(hidden_size, **kwargs)
        self._activation = activation

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size, flatten=False)
        h2h = F.FullyConnected(states, h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size, flatten=False)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(_BaseGatedCell):
    """LSTM cell, gate order [i, f, g, o] (reference rnn_cell.LSTMCell)."""

    _num_gates = 4

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def hybrid_forward(self, F, inputs, h, c, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        ng = 4 * self._hidden_size
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=ng,
                               flatten=False)
        h2h = F.FullyConnected(h, h2h_weight, h2h_bias, num_hidden=ng,
                               flatten=False)
        gates = i2h + h2h
        i, f, g, o = F.split(gates, num_outputs=4, axis=-1)
        i = F.sigmoid(i)
        f = F.sigmoid(f)
        g = F.tanh(g)
        o = F.sigmoid(o)
        c2 = f * c + i * g
        h2 = o * F.tanh(c2)
        return h2, [h2, c2]


class GRUCell(_BaseGatedCell):
    """GRU cell, gates [r, z, n], n = tanh(i2h_n + r * h2h_n)."""

    _num_gates = 3

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        ng = 3 * self._hidden_size
        prev = states
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=ng,
                               flatten=False)
        h2h = F.FullyConnected(prev, h2h_weight, h2h_bias, num_hidden=ng,
                               flatten=False)
        xr, xz, xn = F.split(i2h, num_outputs=3, axis=-1)
        hr, hz, hn = F.split(h2h, num_outputs=3, axis=-1)
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        n = F.tanh(xn + r * hn)
        out = (1.0 - z) * n + z * prev
        return out, [out]


class SequentialRNNCell(RecurrentCell):
    """Stack cells layer-wise (reference SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, func=func, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, st = cell(inputs, states[p:p + n])
            next_states.extend(st)
            p += n
        return inputs, next_states

    def forward(self, inputs, states):
        return self.__call__(inputs, states)

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]


class HybridSequentialRNNCell(SequentialRNNCell):
    """Same stacking; kept for API parity (cells hybridize individually)."""


class ModifierCell(HybridRecurrentCell):
    """Base for cells wrapping another cell (reference ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__(prefix=None, params=None)
        base_cell._modified = True
        self.base_cell = base_cell
        self.register_child(base_cell, "base_cell")

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size=batch_size, func=func,
                                           **kwargs)
        self.base_cell._modified = True
        return begin

    def __repr__(self):
        return f"{type(self).__name__}({self.base_cell!r})"


class DropoutCell(HybridRecurrentCell):
    """Apply dropout on the input of every step."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):  # noqa: ARG002
        return []

    def hybrid_forward(self, F, inputs, *states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, list(states)

    def forward(self, inputs, states):
        self._counter += 1
        out = HybridBlock.forward(self, inputs, *states) \
            if states else HybridBlock.forward(self, inputs)
        if isinstance(out, tuple):
            return out
        return out, []


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (Krueger et al.): randomly keep old state."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout; apply per direction"
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def __call__(self, inputs, states):
        from ... import ndarray as nd
        from ... import autograd
        self._counter += 1
        next_output, next_states = self.base_cell(inputs, states)
        if not autograd.is_training():
            return next_output, next_states

        def mask(p, like):
            return nd.random.uniform(low=0.0, high=1.0, shape=like.shape,
                                     ctx=like.ctx) < (1 - p)

        prev_output = self._prev_output
        if prev_output is None:
            prev_output = nd.zeros_like(next_output)
        if self.zoneout_outputs > 0:
            m = mask(self.zoneout_outputs, next_output).astype(
                next_output.dtype)
            next_output = m * next_output + (1 - m) * prev_output
        if self.zoneout_states > 0:
            out_states = []
            for new_s, old_s in zip(next_states, states):
                m = mask(self.zoneout_states, new_s).astype(new_s.dtype)
                out_states.append(m * new_s + (1 - m) * old_s)
            next_states = out_states
        self._prev_output = next_output
        return next_output, next_states

    def forward(self, inputs, states):
        return self.__call__(inputs, states)


class ResidualCell(ModifierCell):
    """Add the input to the cell's output (residual connection)."""

    def __call__(self, inputs, states):
        self._counter += 1
        output, states = self.base_cell(inputs, states)
        return output + inputs, states

    def forward(self, inputs, states):
        return self.__call__(inputs, states)


class BidirectionalCell(RecurrentCell):
    """Run two cells over the sequence in opposite directions; only usable
    via ``unroll`` (reference BidirectionalCell contract)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix=None, params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):  # noqa: ARG002
        raise MXNetError(
            "BidirectionalCell cannot be stepped; use unroll() "
            "(reference contract)")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, func=func, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as nd
        self.reset()
        seq, axis, batch = _format_sequence(length, inputs, layout, False)
        length = len(seq)
        states = begin_state if begin_state is not None \
            else self.begin_state(batch, ctx=seq[0].ctx, dtype=seq[0].dtype)
        l_cell, r_cell = self._children.values()
        nl = len(l_cell.state_info())
        l_out, l_states = l_cell.unroll(
            length, seq, states[:nl], layout="NTC" if axis == 1 else layout,
            merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            r_seq = list(reversed(seq))
        else:
            # per-sample reverse so each sample's VALID portion is
            # front-aligned for the backward cell (reference SequenceReverse
            # with use_sequence_length — plain reversed() would feed padding
            # first for short samples)
            stacked = nd.stack(*seq, axis=0)  # (T, N, C)
            rev = nd.sequence_reverse(stacked, valid_length.astype("float32"),
                                      use_sequence_length=True)
            r_seq = [rev[t] for t in range(length)]
        r_out, r_states = r_cell.unroll(
            length, r_seq, states[nl:],
            layout="NTC" if axis == 1 else layout, merge_outputs=False,
            valid_length=valid_length)
        if valid_length is None:
            r_out = list(reversed(r_out))
        else:
            # un-reverse per sample (same op is its own inverse)
            stacked = nd.stack(*r_out, axis=0)
            rev = nd.sequence_reverse(stacked, valid_length.astype("float32"),
                                      use_sequence_length=True)
            r_out = [rev[t] for t in range(length)]
        outputs = [nd.concat(lo, ro, dim=-1) for lo, ro in zip(l_out, r_out)]
        if merge_outputs is None:
            merge_outputs = not isinstance(inputs, (list, tuple))
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, l_states + r_states
