"""gluon.rnn — recurrent layers and cells (reference gluon/rnn/, P7)."""

from .rnn_cell import (RecurrentCell, HybridRecurrentCell, RNNCell, LSTMCell,
                       GRUCell, SequentialRNNCell, HybridSequentialRNNCell,
                       DropoutCell, ZoneoutCell, ResidualCell,
                       BidirectionalCell, ModifierCell)  # noqa: F401
from .rnn_layer import RNN, LSTM, GRU  # noqa: F401
