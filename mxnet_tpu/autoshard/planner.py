"""The analytic layout planner (ISSUE 14 tentpole layer 3).

Given a model's named param tree, a global batch, and a per-device HBM
budget, the planner enumerates candidate layouts —

    mesh shapes  ×  rule packs  ×  microbatch counts  ×  remat policy

— scores each one with ``costmodel.estimate_memory`` (does it FIT the
budget?) and an analytic roofline step-time model (which fitter is
FASTEST?), and emits a :class:`Plan` that ``parallel.TrainStep`` consumes
directly.  This closes ROADMAP 3's loop: the fits-per-shape crossover
table PROFILE.md r9 asked a human to read is now a function call.

Everything here is hardware-free and DETERMINISTIC: the same inputs
always produce the same plan (and byte-identical ``plan.json`` — the CI
golden check), because the search is an exhaustive sorted enumeration
over analytic scores with a total tie-break order, no timestamps, no
randomness.

Layout vocabulary (one candidate = one point in this grid):

- **mesh shape** — every factorization of ``n_devices`` over the axes
  (dp, fsdp, tp, sp).  ``sp`` candidates require a known ``seq``
  divisible by the axis; the batch must divide by ``dp*fsdp``
  (per-microbatch, so ``batch % (n_micro * dp * fsdp) == 0``).
- **rule pack** — chosen by the axes present: no model-parallel axis ⇒
  replicated (None), tp/sp only ⇒ the family's megatron pack
  (``llama``...), any fsdp ⇒ the family's ZeRO-3 pack
  (``llama_fsdp``..., which also carries the tp dims).
- **data_spec** — dim0 over ``('dp', 'fsdp')`` (whichever present),
  dim1 (tokens) over ``sp`` when the mesh carries it.
- **n_micro** — 1, 2, 4, ... up to MXNET_AUTOSHARD_MAX_MICRO.
- **remat** — tried LAST (the estimator's remat activation model is not
  cross-checkable against XLA:CPU's compiled peak — see
  ``estimate_memory``'s docstring), so a remat'd candidate wins only
  when nothing else fits.

"Fastest among fitters" ranking: fitters order by the crossover
doctrine first — no remat before remat, fewer model-parallel ways
before more (per-layer collective LATENCY is what a hardware-free byte
model cannot see, so a pure-dp layout outranks an equal-fit tp split),
fewer microbatches before more — and the analytic step-time model
decides within a class: per-device flops at 6·P·tokens (plus the remat
recompute third and the microbatch weight re-reads), HBM traffic from
the estimate's live set, collective bytes from ring-allreduce /
gather-scatter formulas, against ``costmodel.peak_flops()`` /
``peak_hbm_bytes_per_s()`` with interconnect ≈ HBM/10 (the TPU ICI:HBM
ratio class).  The model ranks layouts; it does not promise wall-clock
— BENCH lanes measure that.
"""

from __future__ import annotations

import json

from ..base import MXNetError
from .. import config as _config
from .. import telemetry as _tel
from ..telemetry import costmodel as _cm
from ..telemetry import tracer as _ttrace

__all__ = ["Plan", "plan", "enumerate_candidates", "load_plan",
           "infer_family", "zoo_shapes", "PLAN_VERSION"]

PLAN_VERSION = 1

_M_CANDIDATES = _tel.counter(
    "mxnet_autoshard_candidates_total",
    "Layout candidates the planner enumerated and scored.")
_M_FITS = _tel.counter(
    "mxnet_autoshard_fits_total",
    "Candidates whose estimated per-device HBM fit the budget.")
_M_PLANS = _tel.counter(
    "mxnet_autoshard_plans_total",
    "Plans emitted (one per successful plan() call).")
_M_NO_FIT = _tel.counter(
    "mxnet_autoshard_no_fit_total",
    "plan() calls where NO candidate fit the budget.")

# axis enumeration order == mesh axis order convention (outermost dp,
# ICI-local model axes inner) — the scaling-playbook order DeviceMesh
# documents
_AXES = ("dp", "fsdp", "tp", "sp")

_FAMILIES = ("llama", "bert", "transformer")

# family fingerprints over param names (most specific first): llama's
# gate/up pair, the MT transformer's fused self/cross projections,
# BERT's fused qkv
_FAMILY_PAT = (
    ("llama", ("gate_weight", "q_weight")),
    ("transformer", ("self_qkv_weight", "cross_kv_weight")),
    ("bert", ("attn_qkv_weight", "ffn1_weight")),
)


def infer_family(names):
    """'llama' | 'bert' | 'transformer' | None from param names."""
    names = list(names)
    for fam, pats in _FAMILY_PAT:
        if all(any(n.endswith(p) for n in names) for p in pats):
            return fam
    return None


def zoo_shapes(model, vocab=32000):
    """``(shapes, family)`` — the param-SHAPE table for a zoo config
    name, matching the real models' rule-relevant param naming, so
    layouts for e.g. ``llama3_8b`` plan without building any weights.
    The ONE copy the CLI and the tests share (drift between a
    hand-rolled table and the zoo naming would silently desync the
    committed plan golden)."""
    from ..gluon.model_zoo.llama import LLAMA_CONFIGS
    if model in LLAMA_CONFIGS:
        L, U, H, A, KV = LLAMA_CONFIGS[model]
        hd = U // A
        shapes = {"model_tok_weight": (vocab, U)}
        for i in range(L):
            p = f"model_layer{i}_"
            shapes.update({
                p + "attn_norm_weight": (U,), p + "q_weight": (U, U),
                p + "k_weight": (hd * KV, U),
                p + "v_weight": (hd * KV, U),
                p + "o_weight": (U, U), p + "mlp_norm_weight": (U,),
                p + "gate_weight": (H, U), p + "up_weight": (H, U),
                p + "down_weight": (U, H),
            })
        shapes["model_final_norm_weight"] = (U,)
        shapes["model_lm_head_weight"] = (vocab, U)
        return shapes, "llama"
    from ..gluon.model_zoo.bert import _BERT_CONFIGS
    if model in _BERT_CONFIGS:
        L, U, H, _A = _BERT_CONFIGS[model][:4]
        shapes = {"bert_word_weight": (vocab, U),
                  "bert_position_weight": (512, U)}
        for i in range(L):
            p = f"bert_layer{i}_"
            shapes.update({
                p + "attn_qkv_weight": (3 * U, U),
                p + "attn_qkv_bias": (3 * U,),
                p + "attn_proj_weight": (U, U),
                p + "ffn1_weight": (H, U), p + "ffn1_bias": (H,),
                p + "ffn2_weight": (U, H),
            })
        shapes["bert_decoder_weight"] = (vocab, U)
        return shapes, "bert"
    raise MXNetError(
        f"autoshard.zoo_shapes: unknown zoo model {model!r} (known: "
        "llama_*/bert_* configs)")


def _divisor_splits(n, k):
    """All k-tuples of positive ints whose product is n, sorted."""
    if k == 1:
        return [(n,)]
    out = []
    for d in range(1, n + 1):
        if n % d == 0:
            out.extend((d,) + rest for rest in _divisor_splits(n // d,
                                                              k - 1))
    return sorted(out)


def _pack_for(family, fsdp, tp, sp):
    """Rule-pack name for the model-parallel axes present (None =
    replicate)."""
    if family is None or (fsdp == 1 and tp == 1 and sp == 1):
        return None
    if fsdp > 1:
        return f"{family}_fsdp"
    return family


def _data_spec_for(dp, fsdp, sp):
    """dim0 over (dp, fsdp), dim1 (tokens) over sp when present."""
    batch_axes = tuple(a for a, s in (("dp", dp), ("fsdp", fsdp))
                       if s > 1)
    d0 = batch_axes if len(batch_axes) != 1 else batch_axes[0]
    if sp > 1:
        return (d0 if batch_axes else None, "sp")
    return (d0,) if batch_axes else ()


def _data_axes_for(dp, fsdp, sp):
    return tuple(a for a, s in (("dp", dp), ("fsdp", fsdp), ("sp", sp))
                 if s > 1)


def _matmul_param_elems(table):
    """Total elements of rank>=2 params (the flops carriers)."""
    return sum(_numel(shape) for shape, _i in table.values()
               if len(shape) >= 2)


def _numel(shape):
    n = 1
    for s in shape:
        n *= s
    return n


def _micro_ladder(max_micro):
    n, out = 1, []
    while n <= max_micro:
        out.append(n)
        n *= 2
    return out


_MXU_LANES = 128        # TPU MXU systolic-array lane width


def _matmul_efficiency(table, specs, axes, fsdp_drop):
    """Compute-efficiency factor in (0, 1] for a candidate layout: a
    model-parallel split that shrinks a matmul's per-device dim below
    the MXU's 128-lane tile pays proportionally (the classic reason
    fsdp outranks deep tp at moderate width — gather-on-use keeps FULL
    tiles, so fsdp axes don't count against the dims here)."""
    eff = 1.0
    for name, (shape, _i) in table.items():
        spec = specs.get(name, ())
        if len(shape) < 2:
            continue
        nofsdp = _cm._drop_axes(spec, fsdp_drop)
        for d, dim in enumerate(shape):
            div = 1
            if d < len(nofsdp):
                entry = nofsdp[d]
                entry = entry if isinstance(entry, (tuple, list)) \
                    else (entry,) if entry is not None else ()
                for a in entry:
                    div *= axes.get(a, 1)
            if div > 1 and dim % div == 0:
                sharded = dim // div
                full_eff = min(1.0, dim / _MXU_LANES)
                eff = min(eff, min(1.0, sharded / _MXU_LANES) / full_eff)
    return eff


def _step_time_s(cand, est, matmul_elems, tokens, eff=1.0):
    """Analytic per-step seconds for ranking (see module docstring)."""
    n_dev = cand["n_devices"]
    flops = 6.0 * matmul_elems * tokens
    if cand["remat"]:
        flops *= 4.0 / 3.0          # the recompute forward
    compute_s = (flops / n_dev) / _cm.peak_flops(dtype="float32") \
        / max(eff, 1e-3)
    # HBM traffic per device: the live set streams ~once per step, and
    # every EXTRA microbatch re-reads the (sharded) weights
    traffic = est["total_bytes"] \
        + (cand["n_micro"] - 1) * est["params_bytes"]
    hbm_s = traffic / _cm.peak_hbm_bytes_per_s()
    ici = _cm.peak_hbm_bytes_per_s() / 10.0
    comm = 0.0
    dp, fsdp, tp = cand["mesh"].get("dp", 1), cand["mesh"].get("fsdp", 1), \
        cand["mesh"].get("tp", 1)
    if dp > 1:
        # ring allreduce of the (model-sharded) gradients over dp
        comm += 2.0 * est["params_bytes"] * (dp - 1) / dp
    if fsdp > 1:
        # per-microbatch collectives: forward all-gather + backward
        # re-gather + gradient reduce-scatter, each moving the FULL
        # gathered weight bytes regardless of how much of them coexists
        # in memory (fsdp_gather_bytes is the residency-clamped PEAK
        # quantity — wrong for comm accounting)
        comm += 3.0 * est["fsdp_gathered_bytes"] * cand["n_micro"] \
            * (fsdp - 1) / fsdp
    if tp > 1:
        # per-layer activation collectives ~ one live activation set
        comm += 2.0 * est["activation_bytes"] * (tp - 1) / tp
    return max(compute_s, hbm_s) + comm / ici


def enumerate_candidates(model_cfg, n_devices, global_batch, seq=None,
                         family=None, optimizer="adam",
                         multi_precision=False, max_micro=None,
                         allow_remat=True):
    """Score every candidate layout; returns the sorted candidate list
    (best first) WITHOUT committing to a plan.  Each candidate dict
    carries mesh/pack/data_spec/n_micro/remat, the full memory estimate,
    and the analytic step-time score."""
    table = _cm._param_table(model_cfg)
    names = list(table)
    if family is None:
        family = infer_family(names)
    if family is not None and family not in _FAMILIES:
        raise MXNetError(
            f"autoshard: unknown model family {family!r}; options "
            f"{_FAMILIES} (or None for replicated-only planning)")
    if max_micro is None:
        max_micro = max(1, _config.get_int("MXNET_AUTOSHARD_MAX_MICRO", 8))
    tokens = int(global_batch) * int(seq or 1)
    matmul_elems = _matmul_param_elems(table)

    from .. import sharding as _sh
    _spec_cache = {}

    def _specs_for(pack):
        if pack not in _spec_cache:
            if pack is None:
                _spec_cache[pack] = {n: () for n in table}
            else:
                _spec_cache[pack] = _sh.match_partition_rules(
                    _sh.rule_pack(pack),
                    {n: s for n, (s, _i) in table.items()})
        return _spec_cache[pack]

    cands = []
    for dp, fsdp, tp, sp in _divisor_splits(int(n_devices), len(_AXES)):
        if sp > 1 and (seq is None or seq % sp):
            continue        # sp shards the token dim; needs a known seq
        pack = _pack_for(family, fsdp, tp, sp)
        if pack is None and (fsdp > 1 or tp > 1 or sp > 1):
            continue        # no family: model-parallel axes undrivable
        for n_micro in _micro_ladder(max_micro):
            if int(global_batch) % (n_micro * dp * fsdp):
                continue    # each microbatch must shard the batch dim
            for remat in ((False, True) if allow_remat else (False,)):
                mesh = {a: s for a, s in zip(_AXES, (dp, fsdp, tp, sp))
                        if s > 1}
                mesh.setdefault("dp", dp)
                cand = {
                    "mesh": mesh,
                    "n_devices": int(n_devices),
                    "rule_pack": pack,
                    "data_spec": _data_spec_for(dp, fsdp, sp),
                    "n_micro": n_micro,
                    "remat": remat,
                }
                est = _cm.estimate_memory(
                    model_cfg, mesh, pack, batch=global_batch, seq=seq,
                    optimizer=optimizer, multi_precision=multi_precision,
                    data_axes=_data_axes_for(dp, fsdp, sp),
                    n_micro=n_micro, remat=remat)
                eff = _matmul_efficiency(table, _specs_for(pack), mesh,
                                         frozenset(("fsdp",)))
                cand["estimate"] = est
                cand["matmul_eff"] = round(eff, 4)
                cand["step_time_s"] = _step_time_s(
                    cand, est, matmul_elems, tokens, eff=eff)
                cands.append(cand)
    if _ttrace._ENABLED:
        _M_CANDIDATES.inc(len(cands))
    # deterministic total order — the crossover DOCTRINE, not raw model
    # seconds: collective latency per layer is exactly what a
    # hardware-free byte model cannot see, so layouts rank first by how
    # little model parallelism they spend (no remat before remat, fewer
    # model-parallel ways, fewer microbatches — dp-only stays fastest
    # until memory forces the crossover), and the analytic step time
    # decides WITHIN a class (fsdp vs tp vs sp at the same ways, mesh
    # splits of the same axes), with the mesh shape as the final total
    # tie-break.
    def _order(c):
        m = c["mesh"]
        mp_ways = m.get("fsdp", 1) * m.get("tp", 1) * m.get("sp", 1)
        return (c["remat"], mp_ways, c["n_micro"],
                round(c["step_time_s"], 12), sorted(m.items()))
    cands.sort(key=_order)
    return cands, family


class Plan:
    """One chosen layout: everything ``parallel.TrainStep`` needs.

    ``TrainStep(net, loss_fn, opt, plan=plan)`` builds the mesh from
    ``mesh_axes``/``mesh_sizes``, resolves ``rule_pack`` through
    ``sharding.rule_pack``, and takes ``data_spec``/``n_micro``/``remat``
    as its defaults.  ``save()``/``load_plan()`` round-trip the
    deterministic ``plan.json`` artifact (sorted keys, no timestamps —
    the same inputs produce byte-identical files, which CI goldens)."""

    def __init__(self, mesh_axes, mesh_sizes, rule_pack, data_spec,
                 n_micro, remat, estimate, step_time_s, inputs,
                 search=None):
        self.mesh_axes = tuple(mesh_axes)
        self.mesh_sizes = tuple(int(s) for s in mesh_sizes)
        self.rule_pack = rule_pack
        self.data_spec = _untuple_spec(data_spec)
        self.n_micro = int(n_micro)
        self.remat = bool(remat)
        self.estimate = dict(estimate)
        self.step_time_s = float(step_time_s)
        self.inputs = dict(inputs)
        self.search = dict(search or {})

    # -- TrainStep consumption ----------------------------------------------
    def build_mesh(self, devices=None):
        from .. import parallel
        return parallel.DeviceMesh(shape=self.mesh_sizes,
                                   axis_names=self.mesh_axes,
                                   devices=devices)

    def rules(self):
        if self.rule_pack is None:
            return None
        from .. import sharding as _sh
        return _sh.rule_pack(self.rule_pack)

    @property
    def mesh_shape(self):
        return dict(zip(self.mesh_axes, self.mesh_sizes))

    # -- serialization -------------------------------------------------------
    def to_dict(self):
        return {
            "version": PLAN_VERSION,
            "mesh": {"axes": list(self.mesh_axes),
                     "shape": list(self.mesh_sizes)},
            "rule_pack": self.rule_pack,
            "data_spec": _spec_to_json(self.data_spec),
            "n_micro": self.n_micro,
            "remat": self.remat,
            "estimate": self.estimate,
            "step_time_s": round(self.step_time_s, 9),
            "inputs": self.inputs,
            "search": self.search,
        }

    def to_json(self):
        return json.dumps(self.to_dict(), indent=2, sort_keys=True) + "\n"

    def save(self, path):
        with open(path, "w") as f:
            f.write(self.to_json())
        return path

    @classmethod
    def from_dict(cls, d):
        if d.get("version") != PLAN_VERSION:
            raise MXNetError(
                f"plan.json version {d.get('version')!r} != "
                f"{PLAN_VERSION} (regenerate with tools/autoshard.py)")
        return cls(d["mesh"]["axes"], d["mesh"]["shape"], d["rule_pack"],
                   _spec_from_json(d["data_spec"]), d["n_micro"],
                   d["remat"], d["estimate"], d["step_time_s"],
                   d.get("inputs", {}), d.get("search", {}))

    def __repr__(self):
        dims = "x".join(f"{a}{s}" for a, s in
                        zip(self.mesh_axes, self.mesh_sizes))
        return (f"Plan({dims}, pack={self.rule_pack}, "
                f"data_spec={self.data_spec}, n_micro={self.n_micro}, "
                f"remat={self.remat}, "
                f"est={self.estimate.get('total_bytes', 0) / 1e6:.1f}MB)")


def _untuple_spec(spec):
    if spec is None:
        return None
    return tuple(tuple(e) if isinstance(e, list) else e for e in spec)


def _spec_to_json(spec):
    if spec is None:
        return None
    return [list(e) if isinstance(e, tuple) else e for e in spec]


def _spec_from_json(spec):
    return None if spec is None else _untuple_spec(tuple(spec))


def load_plan(path):
    """Read a ``plan.json`` back into a :class:`Plan`."""
    with open(path) as f:
        return Plan.from_dict(json.load(f))


def plan(model_cfg, global_batch, n_devices=None, seq=None,
         hbm_budget_bytes=None, family=None, optimizer="adam",
         multi_precision=False, max_micro=None, allow_remat=True,
         keep_candidates=3, candidates=None):
    """Pick the fastest layout that fits ``hbm_budget_bytes`` per device.

    ``model_cfg`` is a Block (post-init), ParameterDict, or
    ``{name: shape}`` dict; ``hbm_budget_bytes`` None means the knob
    ``MXNET_AUTOSHARD_HBM_GB`` (0/unset ⇒ unbounded: the planner ranks
    purely on speed).  ``candidates`` reuses a scored
    ``(cands, family)`` pair from :func:`enumerate_candidates` — a
    caller that already swept the grid for display (the CLI's table)
    must not pay for, or double-count in telemetry, a second sweep.
    Raises when NOTHING fits — with the best near-miss in the message,
    which is the OOM verdict the dryrun lane asserts for the dp-only
    layout.  Returns a :class:`Plan`."""
    import jax
    if n_devices is None:
        n_devices = len(jax.devices())
    if hbm_budget_bytes is None:
        gb = _config.get_float("MXNET_AUTOSHARD_HBM_GB", 0.0)
        hbm_budget_bytes = int(gb * 2 ** 30) if gb > 0 else None
    if candidates is not None:
        cands, family = candidates
    else:
        cands, family = enumerate_candidates(
            model_cfg, n_devices, global_batch, seq=seq, family=family,
            optimizer=optimizer, multi_precision=multi_precision,
            max_micro=max_micro, allow_remat=allow_remat)
    if not cands:
        raise MXNetError(
            f"autoshard: no layout candidates for n_devices={n_devices} "
            f"batch={global_batch} (batch must divide by dp*fsdp*n_micro)")
    fits = [c for c in cands
            if hbm_budget_bytes is None
            or c["estimate"]["total_bytes"] <= hbm_budget_bytes]
    enabled = _ttrace._ENABLED
    if enabled:
        _M_FITS.inc(len(fits))
    if not fits:
        if enabled:
            _M_NO_FIT.inc()
        best = min(cands, key=lambda c: c["estimate"]["total_bytes"])
        raise MXNetError(
            f"autoshard: NO layout fits {hbm_budget_bytes} bytes/device "
            f"for batch {global_batch} on {n_devices} devices; closest "
            f"is {best['mesh']} n_micro={best['n_micro']} "
            f"remat={best['remat']} at "
            f"{best['estimate']['total_bytes']} bytes")
    chosen = fits[0]
    if enabled:
        _M_PLANS.inc()
    mesh = chosen["mesh"]
    axes = tuple(a for a in _AXES if a in mesh)
    runners = [{
        "mesh": c["mesh"], "rule_pack": c["rule_pack"],
        "n_micro": c["n_micro"], "remat": c["remat"],
        "total_bytes": c["estimate"]["total_bytes"],
        "step_time_s": round(c["step_time_s"], 9),
    } for c in fits[:keep_candidates]]
    return Plan(
        mesh_axes=axes,
        mesh_sizes=tuple(mesh[a] for a in axes),
        rule_pack=chosen["rule_pack"],
        data_spec=chosen["data_spec"],
        n_micro=chosen["n_micro"],
        remat=chosen["remat"],
        estimate=chosen["estimate"],
        step_time_s=chosen["step_time_s"],
        inputs={
            "n_devices": int(n_devices),
            "global_batch": int(global_batch),
            "seq": None if seq is None else int(seq),
            "hbm_budget_bytes": hbm_budget_bytes,
            "family": family,
            "optimizer": optimizer,
            "multi_precision": bool(multi_precision),
        },
        search={
            "considered": len(cands),
            "fitting": len(fits),
            "top": runners,
        })
