"""Auto-sharder (ISSUE 14, ROADMAP 3): pick mesh shape + rule pack +
microbatch/remat under a per-device HBM budget, analytically.

    from mxnet_tpu import autoshard
    p = autoshard.plan(net, global_batch=512, seq=2048,
                       hbm_budget_bytes=16 << 30)
    step = parallel.TrainStep(net, loss_fn, "adam", plan=p)
    p.save("plan.json")

CLI: ``tools/autoshard.py``.  See planner.py for the search space and
the determinism contract.
"""

from .planner import (Plan, plan, enumerate_candidates, load_plan,
                      infer_family, zoo_shapes, PLAN_VERSION)

__all__ = ["Plan", "plan", "enumerate_candidates", "load_plan",
           "infer_family", "zoo_shapes", "PLAN_VERSION"]
