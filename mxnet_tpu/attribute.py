"""mx.attribute — AttrScope (reference python/mxnet/attribute.py).

``with mx.AttrScope(__ctx_group__='dev1'):`` attaches string attributes to
every symbol created inside the scope — the mechanism behind group2ctx
model parallelism and lr_mult/wd_mult symbol annotations upstream.  Here
the dunder attrs ride along in ``Symbol._attrs`` (excluded from operator
kwargs at execution) and are consumed by whatever pass cares — e.g.
``__ctx_group__`` maps to mesh-axis assignment per SURVEY §7.1 N6.
"""

from __future__ import annotations

import threading

__all__ = ["AttrScope"]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = [AttrScope()]
    return _tls.stack


class AttrScope:
    def __init__(self, **attrs):
        for k in attrs:
            if not (k.startswith("__") and k.endswith("__")):
                raise ValueError(
                    f"AttrScope keys must be __dunder__ strings, got {k!r} "
                    "(reference convention: user attrs are namespaced)")
        self._attrs = {k: str(v) for k, v in attrs.items()}

    @staticmethod
    def current():
        return _stack()[-1]

    def get(self, attrs=None):
        """Merge scope attrs under explicitly-passed ones."""
        if not self._attrs:
            return dict(attrs or {})
        out = dict(self._attrs)
        out.update(attrs or {})
        return out

    def __enter__(self):
        # nested scopes accumulate (reference behavior); the bound object
        # IS the merged scope so `as sc` agrees with AttrScope.current()
        merged = AttrScope()
        merged._attrs = {**AttrScope.current()._attrs, **self._attrs}
        _stack().append(merged)
        return merged

    def __exit__(self, *exc):
        _stack().pop()
