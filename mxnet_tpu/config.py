"""Single catalog of environment-variable configuration.

The reference reads ~100 ``MXNET_*`` env vars ad-hoc via ``dmlc::GetEnv`` at
point of use (SURVEY §5.6; canonical catalog in the reference's
docs/static_site/src/pages/api/faq/env_var.md).  This rebuild centralizes every
knob here: one typed accessor, one place to document, introspectable via
``mxnet_tpu.runtime``.

Only knobs that are meaningful on the TPU/XLA stack are kept; reference knobs
that are absorbed by XLA (e.g. MXNET_GPU_WORKER_NTHREADS, memory-pool tuning)
are accepted but ignored, so existing launch scripts don't break.
"""

from __future__ import annotations

import os
import threading

__all__ = ["get", "get_bool", "get_int", "get_float", "describe", "KNOWN_VARS"]

# name -> (default, type, help)
KNOWN_VARS = {
    # engine family (reference: src/engine/engine.cc :: CreateEngine)
    "MXNET_ENGINE_TYPE": (
        "ThreadedEnginePerDevice",
        str,
        "Execution engine. 'NaiveEngine' blocks after every op (serialized, "
        "deterministic debugging — reference semantics); anything else keeps "
        "JAX/XLA async dispatch.",
    ),
    "MXNET_EXEC_BULK_EXEC_TRAIN": (
        "1", str, "Accepted for compat; XLA fuses/bulk-schedules automatically."),
    "MXNET_EXEC_BULK_EXEC_INFERENCE": ("1", str, "Accepted for compat; no-op."),
    # memory family — absorbed by XLA/PJRT allocator
    "MXNET_GPU_MEM_POOL_TYPE": ("Round", str, "Accepted for compat; no-op on TPU."),
    "MXNET_GPU_MEM_POOL_RESERVE": ("5", str, "Accepted for compat; no-op on TPU."),
    # kvstore family
    "MXNET_KVSTORE_REDUCTION_NTHREADS": ("4", int, "Compat; reductions run on-device."),
    "MXNET_KVSTORE_BIGARRAY_BOUND": (
        str(1000 * 1000), int,
        "Arrays larger than this (elements) may use reduce_scatter+all_gather "
        "instead of one psum in dist kvstore."),
    "MXNET_KVSTORE_USETREE": ("0", str, "Compat; ICI topology handled by XLA."),
    "MXNET_KVSTORE_BUCKET_MB": (
        "25", float,
        "Gradient-fusion bucket size (MB) for kvstore pushpull_list: dense "
        "uncompressed grads flatten-concat into buckets of at most this many "
        "bytes and reduce with ONE dispatch per bucket (DDP/Horovod-style "
        "fusion). 0 disables fusion (per-key pushpull, bit-identical)."),
    # profiler / telemetry
    "MXNET_PROFILER_AUTOSTART": ("0", int, "Start the profiler at import."),
    "MXNET_PROFILER_MODE": ("0", int, "Compat flag for storage profiling."),
    "MXNET_TELEMETRY": (
        "0", int,
        "If 1, runtime telemetry (span tracer + metrics across dispatch, "
        "kvstore, trainer, dataloader, checkpoint) records from import; "
        "0 leaves it off until telemetry.enable()/profiler.start()."),
    "MXNET_TELEMETRY_BUFFER": (
        "65536", int,
        "Span ring-buffer capacity (events); oldest events drop beyond it."),
    # observability plane (ISSUE 10: aggregation + StepClock + flight rec)
    "MXNET_TELEMETRY_DIR": (
        None, str,
        "Cross-process telemetry collection directory: every process "
        "exports a rank-tagged span+metric snapshot here at exit (and on "
        "flight-recorder dumps); rank 0 / tools/telemetry_report.py merge "
        "the shards into ONE Chrome trace and ONE Prometheus snapshot. "
        "Unset = no export."),
    # analytic performance observatory (ISSUE 12: telemetry.costmodel +
    # telemetry.httpd)
    "MXNET_COSTMODEL": (
        "0", int,
        "If 1, the compile/cost ledger arms at import: every owned jit "
        "boundary (op dispatch, TrainStep, fused optimizer/kvstore "
        "buckets, serving prefill/decode) records per-executable compile "
        "seconds, XLA cost_analysis flops/bytes, and memory_analysis "
        "peak-HBM into telemetry.costmodel.LEDGER (report(cost=True), "
        "/ledger.json, BENCH rows).  0 (default) records nothing; "
        "costmodel.arm() flips it at runtime."),
    "MXNET_COSTMODEL_MEMORY": (
        "1", int,
        "If 1 (default), the armed cost ledger also AOT-compiles each new "
        "executable for memory_analysis (argument/output/temp bytes -> "
        "peak-HBM estimate) — one extra XLA compile per executable; 0 "
        "keeps the cheap trace-only cost_analysis (flops/bytes) alone."),
    "MXNET_PEAK_FLOPS": (
        "0", float,
        "Per-chip peak FLOP/s for analytic-MFU accounting (0 = auto from "
        "the device kind: v5e 197e12 bf16, v4 275e12, v5p 459e12, CPU "
        "5e11; float32 = bf16/4)."),
    "MXNET_PEAK_HBM_GBS": (
        "0", float,
        "Per-chip HBM bandwidth in GB/s for the roofline ridge (0 = auto "
        "from the device kind: v5e 819, v4 1228, v5p 2765, CPU 50)."),
    "MXNET_TELEMETRY_PORT": (
        None, int,
        "If set, a daemon-thread HTTP server exposes the LIVE telemetry "
        "plane on this port: /metrics (Prometheus exposition of the "
        "registry — the scrape surface a replica router dispatches on), "
        "/statusz (knobs, world, stepclock verdict, serving gauges), "
        "/ledger.json (cost + op ledgers).  0 binds an ephemeral port; "
        "unset (default) = no server."),
    # perf-regression observatory (ISSUE 16: telemetry.perfgate +
    # tools/perfgate.py + tools/onchip_sweep.py)
    "MXNET_PERFGATE_BASELINE": (
        None, str,
        "Path of the committed analytic perf baseline the gate diffs "
        "against (tools/perfgate.py --check, /perfgate.json, "
        "telemetry_report --perf-diff).  Unset (default) = the repo's "
        "tests/perf_baseline.json."),
    "MXNET_PERFGATE_LANES": (
        None, str,
        "Comma-separated lane filter for perfgate snapshot/check runs "
        "(e.g. 'bert_headline,trainer_fused_kvstore').  Unset (default) "
        "= every registered lane; a filtered --check is reported as "
        "PARTIAL."),
    "MXNET_PERFGATE_CHILD_TIMEOUT_S": (
        "420", float,
        "Per-lane wall budget for the perfgate snapshot child processes "
        "(each lane compiles + runs its steady-state window on the CPU "
        "backend in a fresh interpreter)."),
    "MXNET_PERFGATE_MFU_BAND": (
        "0.25", float,
        "Relative band for the on-chip sweep's measured-vs-analytic MFU "
        "assertion (tools/onchip_sweep.py, PROFILE.md r10 protocol: "
        "analytic MFU counts ALL XLA-emitted flops, so it sits a few "
        "percent above the hand-derived number)."),
    "MXNET_STEPCLOCK_WINDOW": (
        "64", int,
        "Steps the StepClock keeps for the rolling input-/comms-/compute-"
        "bound verdict and telemetry.report()'s phase medians."),
    "MXNET_FLIGHTREC": (
        "1", int,
        "If 1 (default), the crash flight recorder arms at import: "
        "unhandled exceptions, deadline-exceeded, chaos 'exit' faults, "
        "SIGTERM, and SIGUSR2 (on demand) each dump a bounded postmortem "
        "(last spans, metric state, chaos sites, resolved knobs) per "
        "rank.  0 disables the dumps and installs no handlers."),
    "MXNET_FLIGHTREC_DIR": (
        None, str,
        "Directory for flight-recorder dumps (default: MXNET_TELEMETRY_DIR "
        "when set, else ~/.cache/mxnet_tpu/flightrec — never the working "
        "tree; spawned workers inherit the env so one job-wide redirect "
        "covers every rank)."),
    "MXNET_FLIGHTREC_SPANS": (
        "256", int,
        "Most-recent trace events included in each flight-recorder dump."),
    "MXNET_FLIGHTREC_MAX_DUMPS": (
        "16", int,
        "Flight-recorder dump-file cap per process (rate limit: a retry "
        "loop hitting deadlines must not flood the disk)."),
    # data pipeline
    "MXNET_CPU_WORKER_NTHREADS": ("1", int, "Worker threads for host-side data aug."),
    # multi-core decode pipeline (ISSUE 7: io/pipeline.py)
    "MXNET_IO_POOL": (
        "1", int,
        "If 1 (default), ImageRecordIter(preprocess_threads>1) and "
        "DataLoader over decode-aware datasets run the shared-memory "
        "multi-process decode pipeline (bit-identical batches); 0 forces "
        "in-process decode everywhere."),
    "MXNET_IO_PREFETCH": (
        "2", int,
        "Batches the decode pipeline keeps in flight ahead of the "
        "consumer (shared-memory slab count is this + 1 — host memory "
        "scales with it).  2 = double buffering: one batch consumed, two "
        "decoding."),
    "MXNET_IO_CHUNK": (
        "0", int,
        "Records per decode-pool task.  0 = auto (one task wave per "
        "batch across the worker pool; stragglers hide behind the next "
        "prefetched batch's queued chunks)."),
    "MXNET_IO_TIMEOUT_S": (
        "60", float,
        "Deadline (seconds) on one decode chunk.  A worker that blows it "
        "is treated as hung: the pool is hard-killed (a late write into "
        "a recycled slab must be impossible), the chunk re-decodes "
        "in-process, and the degradation ladder (MXNET_DATALOADER_RETRIES) "
        "advances."),
    # testing / RNG (reference: tests/python/unittest/common.py)
    "MXNET_TEST_SEED": (None, int, "Per-test RNG seed override."),
    "MXNET_MODULE_SEED": (None, int, "Module-wide RNG seed override."),
    # TPU-rebuild-specific
    "MXNET_TPU_DEFAULT_MATMUL_PRECISION": (
        "highest", str,
        "jax matmul precision for float32 ops: default|high|highest. "
        "'highest' gives true-f32 MXNet numerics (3/6-pass bf16 on the MXU); "
        "set 'default' to trade accuracy for raw MXU throughput."),
    "MXNET_FUSED_ATTENTION": (
        "1", int,
        "If 1 (default), contrib.masked_selfatt lowers to the Pallas flash "
        "attention kernel on TPU (seq multiple of 128); 0 forces the dense "
        "masked-softmax fallback everywhere."),
    "MXNET_FLASH_MIN_SEQ": (
        "256", int,
        "Shortest sequence the flash kernel handles; below it the dense "
        "path wins on measured v5e step time (XLA's fused softmax beats "
        "per-grid-step kernel cost at tiny (L, L) tiles)."),
    "MXNET_TPU_JIT_IMPERATIVE": (
        "1", int,
        "If 1, imperative op dispatch goes through a per-(op,shape,dtype,attrs) "
        "jax.jit cache; if 0, ops run op-by-op eagerly."),
    "MXNET_SHOW_ENV": ("0", int, "Print the env-var catalog at import (1.7 parity)."),
    "MXNET_GELU_TANH": (
        "0", int,
        "If 1, gelu (the op, LeakyReLU act_type='gelu', and "
        "gluon.nn.GELU) defaults to the tanh approximation "
        "0.5x(1+tanh(sqrt(2/pi)(x+0.044715x^3))) instead of the exact erf "
        "form — the cheaper PROFILE.md lever for the seq-512 MFU target. "
        "An explicit approximate= attr always wins; read when an op/block "
        "first resolves, so set it before building the model."),
    "MXNET_PARAMS_FORMAT": (
        "npz", str,
        "Default mx.nd.save container: 'npz' (rich: sparse/bf16) or 'dmlc' "
        "(the reference's byte-compatible .params layout). load() "
        "auto-detects both."),
    "MXNET_CHECKPOINT_KEEP": (
        "3", int,
        "How many step checkpoints mx.checkpoint.CheckpointManager retains."),
    "MXNET_CHECKPOINT_SHARDED": (
        "0", int,
        "If 1, mesh-sharded params save as sharded jax.Arrays (orbax "
        "writes shards in parallel per host — the pod-scale path); 0 "
        "(default) gathers them to host arrays first, making the "
        "checkpoint topology-free (restores on any mesh or none)."),
    # GSPMD sharding engine (ISSUE 8: mxnet_tpu.sharding)
    "MXNET_SHARDING_SKIP_ALLREDUCE": (
        "1", int,
        "If 1 (default), gluon.Trainer skips the local/device kvstore "
        "gradient reduction for params flagged Parameter.mesh_reduced "
        "(a mesh-jitted step already psum'd their grads — reducing again "
        "would double-count); dist stores always reduce. 0 restores the "
        "unconditional reduction."),
    # auto-sharder / memory-axis scale (ISSUE 14: mxnet_tpu.autoshard)
    "MXNET_MICROBATCH": (
        "1", int,
        "Trace-time default for parallel.TrainStep(n_micro=): gradient-"
        "accumulation microbatch count per step (the batch splits into "
        "this many slices scanned with fixed-association accumulation "
        "and ONE optimizer update). 1 (default) keeps the original "
        "single-pass step, bit-identically."),
    "MXNET_REMAT": (
        "0", int,
        "Trace-time default for parallel.TrainStep(remat=): if 1, the "
        "net forward runs under gluon.utils.remat_call so activations "
        "are recomputed during backward instead of saved (memory for "
        "compute; single-output nets only)."),
    "MXNET_AUTOSHARD_HBM_GB": (
        "0", float,
        "Default per-device HBM budget (GB) for autoshard.plan() and "
        "tools/autoshard.py when the caller passes none; 0 (default) "
        "means unbounded — the planner ranks purely on speed."),
    "MXNET_AUTOSHARD_MAX_MICRO": (
        "8", int,
        "Largest microbatch count the auto-sharder may propose while "
        "searching for a fitting layout (candidates double from 1 up "
        "to this bound)."),
    # resilience family (ISSUE 3: mx.resilience)
    "MXNET_KVSTORE_TIMEOUT_S": (
        "300", float,
        "Deadline (seconds) on blocking dist-kvstore calls (bring-up, "
        "allreduce, barrier): a dead/wedged peer raises KVStoreTimeoutError "
        "instead of hanging forever. 0 disables the bound."),
    "MXNET_RESILIENCE_MAX_RETRIES": (
        "3", int,
        "Re-attempts a Retry policy makes after the first failure of a "
        "transient (retryable) operation; 0 fails fast."),
    "MXNET_RESILIENCE_BACKOFF_S": (
        "0.05", float,
        "Base backoff (seconds) before retry attempt k sleeps "
        "backoff * 2^k (with +/-25% jitter)."),
    "MXNET_RESILIENCE_BACKOFF_MAX_S": (
        "2", float, "Cap on the exponential retry backoff (seconds)."),
    "MXNET_RESILIENCE_SIGTERM_SAVE": (
        "1", int,
        "If 1, mx.checkpoint.auto_resume installs a SIGTERM hook that "
        "checkpoints after the in-flight step and exits cleanly "
        "(preemption-safe save); 0 leaves the default signal behavior."),
    "MXNET_DATALOADER_RETRIES": (
        "2", int,
        "Worker-pool batch failures DataLoader absorbs via in-process "
        "refetch before permanently degrading to single-process loading."),
    "MXNET_LOCKCHECK": (
        "0", int,
        "If 1, locks created through analysis.tracked() record their "
        "acquisition order and raise LockOrderError on a cycle — the "
        "runtime twin of graftcheck GC06 (debug/test builds; disarmed "
        "locks are returned raw, zero overhead)."),
    "MXNET_CHAOS": (
        "0", int,
        "If 1, arm chaos faults from MXNET_CHAOS_SITES at import "
        "(mx.resilience.chaos fault injection for recovery testing)."),
    "MXNET_CHAOS_SITES": (
        None, str,
        "Comma list of faults to arm when MXNET_CHAOS=1: "
        "'site:kind[:times[:delay_s]]' with kind in "
        "delay|transient|fatal|exit, e.g. 'kvstore.allreduce:transient:2'."),
    # distributed bring-up (tools/launch.py writes these per worker; the
    # dist kvstore reads them at _ensure_dist)
    "MXNET_DIST_COORDINATOR": (
        None, str,
        "host:port of the jax.distributed rendezvous coordinator "
        "(JAX_COORDINATOR_ADDRESS also honored); unset = single-process."),
    "MXNET_DIST_NUM_WORKERS": (
        "1", int, "World size the dist kvstore rendezvous waits for."),
    "MXNET_DIST_RANK": (
        "0", int, "This worker's process id in the dist kvstore world."),
    # elastic controller (ISSUE 11: resilience/controller.py +
    # tools/elastic_launch.py; the *_DIR/INCARNATION/WORLD_TARGET vars are
    # WRITTEN by the controller into each worker's env)
    "MXNET_ELASTIC_MIN_WORKERS": (
        "1", int,
        "Smallest world size the elastic controller will shrink to on "
        "worker death before restarting at the same size."),
    "MXNET_ELASTIC_MAX_RESTARTS": (
        "8", int,
        "Unplanned whole-job restarts the controller performs before "
        "declaring the job dead (planned grow-backs are free); each "
        "burns a Retry-policy exponential backoff."),
    "MXNET_ELASTIC_REGROW_STEPS": (
        "0", int,
        "Committed checkpoint steps a DEGRADED (shrunk) incarnation must "
        "add before the controller drains it and grows back to the "
        "target world.  0 = never grow back automatically."),
    "MXNET_ELASTIC_HEARTBEAT_S": (
        "2", float,
        "Worker heartbeat interval (resilience.heartbeat daemon thread; "
        "started by the dist kvstore at bring-up when a heartbeat dir "
        "is configured)."),
    "MXNET_ELASTIC_HEARTBEAT_DIR": (
        None, str,
        "Directory of per-rank heartbeat files (hb-rank<R>.json, atomic "
        "rewrites).  The elastic controller injects one per incarnation; "
        "unset = heartbeats off."),
    "MXNET_ELASTIC_HANG_S": (
        "60", float,
        "Heartbeat staleness after which the controller declares a "
        "worker hung and SIGKILLs it (a wedged rank holds every peer "
        "hostage inside the collective).  0 disables hang detection."),
    "MXNET_ELASTIC_STRAGGLER_FACTOR": (
        "0", float,
        "Straggler threshold fed by the stepclock verdicts in the "
        "heartbeats: when every peer is comms-bound and exactly one "
        "rank is not, and its compute median exceeds this factor times "
        "the fastest peer's, the controller kills it and resizes.  "
        "0 (default) disables straggler mitigation."),
    "MXNET_ELASTIC_GRACE_S": (
        "10", float,
        "Drain grace: seconds between the controller's SIGTERM (the "
        "preemption-save path) and SIGKILL when stopping workers."),
    "MXNET_ELASTIC_INCARNATION": (
        "0", int,
        "Job incarnation counter the controller injects per (re)start; "
        "workers use it to scope restart-once behaviors and the "
        "heartbeat/flightrec records carry it."),
    "MXNET_ELASTIC_WORLD_TARGET": (
        None, int,
        "The job's TARGET world size, fixed across resizes (injected by "
        "the controller).  Workers shard a fixed data space over it so "
        "training math is world-size-independent; unset = current "
        "world."),
    # optimizer aggregation (reference MXNET_OPTIMIZER_AGGREGATION_SIZE)
    "MXNET_OPTIMIZER_AGGREGATION_SIZE": (
        "4", int,
        "Max same-dtype params fused into one multi-tensor optimizer "
        "dispatch (multi_sgd_update family); 1 disables aggregation. "
        "Only reached when MXNET_OPTIMIZER_FUSED=0."),
    # flat-buffer fused optimizer (ISSUE 5: optimizer_fusion)
    "MXNET_OPTIMIZER_FUSED": (
        "1", int,
        "If 1 (default), adam/sgd updates run as ONE donated jitted "
        "dispatch per dtype bucket over persistent flat state buffers "
        "(optimizer_fusion; bitwise identical to the per-param path); "
        "0 restores per-param updates everywhere."),
    "MXNET_OPTIMIZER_BUCKET_MB": (
        "25", float,
        "Fused-optimizer bucket size bound (MB): same-dtype parameters "
        "group into flat-state buckets of at most this many bytes, one "
        "donated update dispatch each. <= 0 disables optimizer fusion."),
    # serving engine (ISSUE 6: mx.serving — paged KV + continuous batching)
    "MXNET_SERVING_BLOCK_TOKENS": (
        "16", int,
        "Paged-KV block size (token positions per pool block): sequences "
        "allocate cache in blocks of this many tokens and a per-sequence "
        "block table maps positions to blocks, so mixed-length traffic "
        "shares one fixed-shape pool with no retrace."),
    "MXNET_SERVING_MAX_BATCH": (
        "8", int,
        "Decode slots in the continuous batch — the fixed B of the "
        "compiled (B, 1) decode step.  Finished sequences' slots are "
        "backfilled from the queue every iteration."),
    "MXNET_SERVING_MAX_SEQ": (
        "256", int,
        "Longest sequence (prompt + generation) a serving request may "
        "reach; sets each slot's block-table width.  Requests that could "
        "exceed it are rejected at submit."),
    "MXNET_SERVING_NUM_BLOCKS": (
        "0", int,
        "KV pool blocks (plus the reserved scratch block 0).  0 = worst "
        "case (max_batch * blocks_per_seq + 1: no preemption possible); "
        "smaller pools oversubscribe and rely on preemption-by-recompute."),
    "MXNET_SERVING_PREFILL_TOKENS": (
        "64", int,
        "Fixed padded prompt shape (1, P) the prefill step compiles at — "
        "prompts above it are rejected; must be <= MXNET_SERVING_MAX_SEQ."),
    "MXNET_SERVING_SLA_S": (
        "0", float,
        "Default per-request SLA deadline (seconds, submit to finish): "
        "expired requests are evicted (queued or mid-decode) with "
        "RequestDeadlineExceeded — the serving twin of the resilience "
        "Deadline policy.  0 = no deadline; submit(deadline_s=) overrides."),
    "MXNET_SERVING_PREFIX_CACHE": (
        "0", int,
        "If 1, the paged KV cache refcounts blocks and keeps a hash-keyed "
        "prefix index over full blocks of prompt tokens: a prompt sharing "
        "a cached prefix maps those blocks into its table (copy-on-write "
        "on contended writes) and prefills only the tail — bit-identical "
        "to the cold path, >= 2x fewer prefill positions on shared-"
        "system-prompt traffic.  Decoder-only (llama) engines only."),
    "MXNET_SERVING_DRAFT": (
        None, str,
        "Draft-model zoo config name for speculative decoding (e.g. "
        "'llama_tiny'): the replica CLI and serve_bench build it with the "
        "engine's vocab and seed so every replica speculates identically. "
        " Unset (default) = speculation off.  In-process callers pass "
        "ServingEngine(draft_model=) instead."),
    "MXNET_SERVING_SPEC_K": (
        "3", int,
        "Draft tokens proposed per scheduler iteration when speculative "
        "decoding is armed; the target verifies all of them (plus its "
        "own fallback token) in ONE fixed-shape (B, K+1) dispatch — "
        "accept-longest-prefix keeps output bit-identical to plain "
        "greedy decode at any acceptance rate."),
    # serving router tier (ISSUE 13: serving.router + serving.replica —
    # the *_DIR/INDEX vars are WRITTEN by the router into each replica's
    # env, the rest tune the router process itself)
    "MXNET_ROUTER_QUEUE": (
        "64", int,
        "Admission bound on requests outstanding in the router (waiting "
        "for dispatch + dispatched, unfinished).  Submits beyond it are "
        "shed immediately with RouterOverloaded (mxnet_router_shed_total) "
        "so overload degrades p99-bounded instead of collapsing."),
    "MXNET_ROUTER_HEDGE_S": (
        "0", float,
        "Tail-latency hedging: a dispatched request unfinished after this "
        "many seconds is duplicated to a second replica; the first "
        "completion wins and the loser is cancelled.  0 (default) "
        "disables hedging."),
    "MXNET_ROUTER_MAX_RETRIES": (
        "2", int,
        "Times the router resubmits one request to a surviving replica "
        "after the replica serving it died; beyond it the handle fails "
        "with ReplicaDeadError.  Resubmission re-prefills and is "
        "token-identical (greedy decode is deterministic)."),
    "MXNET_ROUTER_MAX_RESPAWNS": (
        "8", int,
        "Per-replica respawn budget: crashes beyond it leave the replica "
        "permanently down (the tier keeps serving on the survivors).  "
        "Respawns back off with the Retry policy's exponential schedule."),
    "MXNET_ROUTER_HANG_S": (
        "20", float,
        "Replica heartbeat staleness after which the router declares it "
        "hung, SIGKILLs it, resubmits its in-flight requests, and "
        "respawns it.  0 disables hang detection."),
    "MXNET_ROUTER_PING_S": (
        "1", float,
        "Idle-load refresh interval: the router pings each replica this "
        "often so least-loaded dispatch stays fresh between acks."),
    "MXNET_ROUTER_AFFINITY_TOKENS": (
        "16", int,
        "Prompt-prefix length (tokens) hashed for the router's prefix-"
        "affinity dispatch hint: least-loaded TIES prefer the replica "
        "that last served the same prefix hash, so shared-system-prompt "
        "streams hit the per-replica paged-KV prefix cache (bounded "
        "map; dead/busier replicas fall back to the rotating "
        "tie-break).  0 disables the hint."),
    "MXNET_ROUTER_DIR": (
        None, str,
        "Router tier working directory (WRITTEN by the router into each "
        "replica's env): the replica publishes its RPC port file here "
        "and the router keeps its state journal, heartbeats, telemetry "
        "shards, and flight-recorder dumps under it."),
    "MXNET_ROUTER_INDEX": (
        None, int,
        "This replica's index in the router tier (WRITTEN by the router; "
        "also mirrored into MXNET_DIST_RANK so heartbeat files and "
        "telemetry shards are rank-tagged per replica)."),
    # native (C++) fast lanes
    "MXNET_USE_NATIVE": (
        "1", int,
        "0 disables the native recordio scanner / fused JPEG decoder "
        "outright (pure-python fallbacks everywhere)."),
    "MXNET_NATIVE_CACHE": (
        None, str,
        "Directory for on-demand-compiled native libraries when the "
        "package dir is read-only (default ~/.cache/mxnet_tpu)."),
    # flash-attention kernel tuning (single-tile kernels only)
    "MXNET_FLASH_BLOCK_H_FWD": (
        None, int,
        "Force the head-block size of the single-tile flash-attention "
        "FORWARD kernel (must divide the head count; non-divisors fall "
        "through to the auto pick). Unset = VMEM-budget auto-tune."),
    "MXNET_FLASH_BLOCK_H_BWD": (
        None, int,
        "Force the head-block size of the single-tile flash-attention "
        "BACKWARD kernel (same divisibility contract as _FWD)."),
}

_lock = threading.Lock()
_cache: dict = {}


def get(name, default=None):
    """String value of an env var, with catalog defaults."""
    if name in os.environ:
        return os.environ[name]
    if name in KNOWN_VARS:
        d = KNOWN_VARS[name][0]
        return d if d is not None else default
    return default


def _typed(name, default, caster):
    v = get(name)
    if v is None:
        return default
    try:
        return caster(v)
    except (TypeError, ValueError):
        return default


def get_int(name, default=0):
    return _typed(name, default, int)


def get_float(name, default=0.0):
    return _typed(name, default, float)


def get_bool(name, default=False):
    v = get(name)
    if v is None:
        return default
    return str(v).lower() in ("1", "true", "yes", "on")


def describe():
    """Return the full catalog as rows (name, current, default, help)."""
    rows = []
    for name, (default, _typ, doc) in sorted(KNOWN_VARS.items()):
        rows.append((name, get(name), default, doc))
    return rows


if get_bool("MXNET_SHOW_ENV"):
    for _row in describe():
        print("%-40s = %-24s # %s" % (_row[0], _row[1], _row[3]))
