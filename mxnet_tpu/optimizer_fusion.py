"""Flat-buffer fused optimizer updates — multi-tensor apply with donation.

PROFILE.md's step decomposition names the optimizer as the gap between the
seq-512 lane's 0.43 MFU and the 0.45 BASELINE target: the 110M-param
multi-precision adam costs 8.9 ms/step against a ~3.2 ms HBM bound because
the update runs as one small dispatch per parameter, each re-reading
weights and states from HBM.  Every serious trainer fuses here — PyTorch
DDP buckets gradients, NVIDIA Apex runs multi-tensor `FusedAdam` — and
this module is that layer for the TPU rebuild, shaped like PR 2's kvstore
gradient fusion (same `GradBucketer` bucket layout, same bit-identity
contract, same cached-executable discipline):

- Same-dtype dense parameters group, in key order, into size-bounded
  buckets (``MXNET_OPTIMIZER_BUCKET_MB``, default 25) planned by
  ``kvstore.fusion.GradBucketer``.
- Each bucket updates with ONE jitted call whose ``donate_argnums``
  cover every weight and optimizer-state buffer (adam m/v, sgd momentum,
  multi-precision fp32 masters): each buffer is read once and written in
  place, and steady-state dispatch count equals bucket count.
  Executables cache per bucket signature (shapes, dtype, optimizer
  kind, static hyperparams), so the retrace count stays flat
  (``exec_builds()`` is the invariant tests assert).
- The gradient side has two entry modes: per-parameter gradients
  (``fused_update`` — the no-kvstore / in-process path) and ONE flat
  reduced bucket straight off the fused-allreduce wire
  (``fused_update_flat`` — ``KVStoreLocal.pushpull_flat`` hands the
  psum output over and the executable slices it per segment, skipping
  the unflatten/reflatten HBM round trip entirely).
- ``traced=True`` runs the same math inline on traced values —
  ``parallel.TrainStep`` routes its in-trace update through it so the
  fused SPMD step stops paying ~200 dispatch-wrapper traces.

Bit-identity contract: the update math mirrors ``ops/optimizer_ops.py``
formula-for-formula, including scalar promotion (python-float attrs
trace as weak f32, so every dynamic scalar here is rounded to f32 and
then cast to the compute dtype) and the multi-precision
``grad.astype(float32)`` / ``master.astype(weight.dtype)`` casts.
Fused and per-param paths are bitwise identical on every tested
combination; callers may switch freely.

Layout note (measured, XLA:CPU): within one executable, every per-param
output keeps its own buffer.  Concatenating state outputs into one flat
buffer looks attractive (it is how the gradients arrive), but the
fused concat loop carries region-dependent scalars (per-param lr/wd)
and XLA contracts (fma) it differently from the small per-param
kernels — a 1-ulp split that survives ``lax.optimization_barrier``
(fusion inlines straight through barriers).  Per-param output fusions
have the same structure as the reference kernels and round identically;
per-param lr/wd must ride as individual scalar args for the same reason
(an indexed vector load inside the kernel changes codegen).

Donation invariant: callers must NOT alias donated buffers — after a
fused update, previously captured raw ``jax.Array`` references to
weight or state buffers are dead (NDArray handles stay valid; they
re-read the swapped slot).

Fallback rules (exactly like the kvstore fused path): sparse/row-sparse
parameters, ``update_on_kvstore``, loss-scale overflow skips, and
unsupported optimizers keep the per-key path, gated by
``MXNET_OPTIMIZER_FUSED`` (1 enables, 0 restores per-param everywhere).
"""

from __future__ import annotations

import threading
import time as _time

import numpy as _np

from . import config
from . import telemetry as _tel
from .telemetry import costmodel as _costmodel
from .telemetry import tracer as _ttrace

__all__ = ["fusion_enabled", "fusion_active", "supported_kind",
           "bucket_bytes_from_env", "fused_update", "fused_update_flat",
           "traced_update", "plan_trainstep", "planner", "reset",
           "exec_builds", "record_fallback", "record_update",
           "DEFAULT_OPT_BUCKET_MB"]

DEFAULT_OPT_BUCKET_MB = 25.0

# fused-update visibility (ISSUE 5 satellite): dispatches (= buckets),
# parameters riding fused vs falling back, per-bucket host latency
_M_FUSED_UPDATES = _tel.counter(
    "mxnet_optimizer_fused_updates_total",
    "Fused optimizer update calls (one per replica step taking the "
    "bucketed path).")
_M_FUSED_BUCKETS = _tel.counter(
    "mxnet_optimizer_fused_buckets_total",
    "Optimizer buckets dispatched (one donated jitted update each).")
_M_FUSED_PARAMS = _tel.counter(
    "mxnet_optimizer_fused_params_total",
    "Parameters updated through the fused bucket path.")
_M_FALLBACK_PARAMS = _tel.counter(
    "mxnet_optimizer_fused_fallback_params_total",
    "Parameters that fell back to the per-param update path "
    "(sparse / unsupported).")
_M_BUCKET_SECONDS = _tel.histogram(
    "mxnet_optimizer_fused_bucket_seconds",
    "Host-side latency per fused optimizer bucket dispatch.")


def fusion_enabled():
    """MXNET_OPTIMIZER_FUSED knob (default on); 0 restores the per-param
    update path everywhere (bit-identical by contract)."""
    return config.get_int("MXNET_OPTIMIZER_FUSED", 1) != 0


def bucket_bytes_from_env():
    """MXNET_OPTIMIZER_BUCKET_MB → bytes; <= 0 disables fusion."""
    return int(config.get_float("MXNET_OPTIMIZER_BUCKET_MB",
                                DEFAULT_OPT_BUCKET_MB) * (1 << 20))


def supported_kind(optimizer):
    """'adam' / 'sgd' for exactly the optimizers whose update the fused
    executables reproduce bit-for-bit; None for everything else
    (subclasses excluded on purpose — they may override the math)."""
    from . import optimizer as _opt
    t = type(optimizer)
    if t is _opt.Adam:
        return "adam"
    if t is _opt.SGD:
        return "sgd"
    return None


def fusion_active(optimizer):
    """ONE gate for every entry point: knob on, bucket bound positive,
    and the optimizer's math reproduced exactly.  Callers that bypass
    this (e.g. an SGD subclass inheriting update_multi) must fall back
    to their legacy path."""
    return (fusion_enabled() and bucket_bytes_from_env() > 0
            and supported_kind(optimizer) is not None)


# -- bucket planning ---------------------------------------------------------

_lock = threading.Lock()
_planner = None


def planner():
    """Module-wide GradBucketer planning optimizer buckets (the kvstore's
    layout machinery, reused with n_rep=1).  Rebuilt whenever
    MXNET_OPTIMIZER_BUCKET_MB changes, so a runtime knob flip (e.g. the
    PROFILE.md bucket-size sweep) replans instead of half-applying."""
    global _planner
    nbytes = bucket_bytes_from_env()
    with _lock:
        if _planner is None or _planner.bucket_bytes != nbytes:
            from .kvstore.fusion import GradBucketer
            _planner = GradBucketer(nbytes)
        return _planner


def reset():
    """Drop plan + executable caches (tests flip knobs at runtime)."""
    global _planner
    with _lock:
        _planner = None
        _EXEC_CACHE.clear()


# -- state roles -------------------------------------------------------------

def _roles(kind, mp, has_mom):
    if kind == "adam":
        return (("master",) if mp else ()) + ("mean", "var")
    return (("master",) if mp else ()) + (("mom",) if has_mom else ())


def _role_arrays(kind, mp, has_mom, state):
    """Per-param state tree -> NDArrays in _roles order."""
    if kind == "adam":
        if mp:
            master, (m, v) = state
            return [master, m, v]
        m, v = state
        return [m, v]
    if mp:
        master, mom = state
        return [master] + ([mom] if has_mom else [])
    return [state] if has_mom else []


def _offsets(sizes):
    offs, off = [], 0
    for s in sizes:
        offs.append(off)
        off += s
    return tuple(offs), off


# -- the per-param math (shared by jitted executables and traced mode) -------

def _scal(s, dtype):
    """Dynamic scalar → compute dtype, mirroring how the per-param ops see
    python-float attrs: weak-f32 first (jit traces python floats as weak
    f32), then the array-dtype demotion."""
    import jax.numpy as jnp
    if isinstance(s, (int, float)):
        s = _np.float32(s)
    return jnp.asarray(s).astype(dtype)


def _param_update(kind, mp, has_mom, cfg, w, g, sts, lr, wd, rescale,
                  momentum):
    """One parameter's update on raw jax values in their native shapes.
    Mirrors ops/optimizer_ops.py {sgd,sgd_mom,adam}_update plus the
    update_multi_precision wrapper formula-for-formula (same op order,
    same scalar promotion, same mp casts) — this is what makes the fused
    path bitwise identical to the per-param path.  Returns
    (new_weight, new_states_in_role_order)."""
    import jax.numpy as jnp
    beta1, beta2, eps, clip = cfg
    if mp:
        w16 = w
        w = sts[0]                  # fp32 master
        cdt = w.dtype
        g = g.astype(cdt)           # update_multi_precision: grad → f32
    else:
        cdt = w.dtype
    lr = _scal(lr, cdt)
    wd = _scal(wd, cdt)
    g = g * _scal(rescale, cdt)
    if clip is not None and clip >= 0:
        g = jnp.clip(g, -clip, clip)
    if kind == "adam":
        m, v = sts[-2], sts[-1]
        g = g + wd * w
        new_m = beta1 * m + (1 - beta1) * g
        new_v = beta2 * v + (1 - beta2) * jnp.square(g)
        new_w = w - lr * new_m / (jnp.sqrt(new_v) + eps)
        outs = (new_m, new_v)
    elif has_mom:
        new_mom = _scal(momentum, cdt) * sts[-1] - lr * (g + wd * w)
        new_w = w + new_mom
        outs = (new_mom,)
    else:
        new_w = w - lr * (g + wd * w)
        outs = ()
    if mp:
        return new_w.astype(w16.dtype), (new_w,) + outs
    return new_w, outs


# -- cached donated executables ----------------------------------------------

_EXEC_CACHE: dict = {}
_builds = 0


def exec_builds():
    """Executable constructions so far — a steady-state training loop must
    not grow this after its first step (the retrace invariant)."""
    return _builds


def _get_exec(kind, mp, has_mom, shapes, sizes, dtype, cfg, flat_grad):
    global _builds
    key = (kind, mp, has_mom, tuple(shapes), str(dtype), cfg, flat_grad)
    fn = _EXEC_CACHE.get(key)
    if fn is None:
        with _lock:
            fn = _EXEC_CACHE.get(key)
            if fn is None:
                fn = _build_exec(kind, mp, has_mom, tuple(shapes),
                                 tuple(sizes), cfg, flat_grad)
                _EXEC_CACHE[key] = fn
                _builds += 1
    return fn


def _build_exec(kind, mp, has_mom, shapes, sizes, cfg, flat_grad):
    """ONE jitted update for a whole bucket.  Argument layout:
    ``w_0..w_{n-1}, grads (n per-param arrays | 1 flat buffer),
    states (role-major: role0_p0..role0_p{n-1}, role1_p0..),
    lr_0..lr_{n-1}, wd_0..wd_{n-1}, rescale[, momentum]`` →
    ``(w'_0.., states'_role_major..)``.  Weights and states are donated
    — read once, written in place.  Per-param lr/wd ride as individual
    traced SCALARS and every output keeps its own per-param buffer: both
    are bit-identity requirements (see the module docstring's layout
    note on XLA:CPU fma contraction)."""
    import jax

    n = len(shapes)
    offs, _ = _offsets(sizes)
    n_roles = len(_roles(kind, mp, has_mom))
    g_args = 1 if flat_grad else n
    base = n + g_args

    def fn(*args):
        ws = args[:n]
        flats = args[base:base + n_roles * n]
        s0 = base + n_roles * n
        lrs = args[s0:s0 + n]
        wds = args[s0 + n:s0 + 2 * n]
        rescale = args[s0 + 2 * n]
        mom = args[s0 + 2 * n + 1] if has_mom else None
        new_ws = []
        new_states = [[] for _ in range(n_roles)]
        for i in range(n):
            if flat_grad:
                g = args[n][offs[i]:offs[i] + sizes[i]].reshape(shapes[i])
            else:
                g = args[n + i]
            sts = [flats[r * n + i] for r in range(n_roles)]
            new_w, outs = _param_update(kind, mp, has_mom, cfg, ws[i], g,
                                        sts, lrs[i], wds[i], rescale, mom)
            new_ws.append(new_w)
            for r in range(n_roles):
                new_states[r].append(outs[r])
        return tuple(new_ws) + tuple(
            s for role in new_states for s in role)

    donate = tuple(range(n)) + tuple(range(base, base + n_roles * n))
    return _costmodel.wrap_jit(jax.jit(fn, donate_argnums=donate),
                               f"optimizer_fusion.{kind}")


# -- apply -------------------------------------------------------------------

def _static_cfg(optzr, kind):
    clip = optzr.clip_gradient
    clip = float(clip) if clip is not None else -1.0
    if kind == "adam":
        return (optzr.beta1, optzr.beta2, optzr.epsilon, clip)
    return (None, None, None, clip)


def _eff_lr_wd(optzr, kind, indices):
    """Per-param effective lr/wd AFTER counts advanced — adam's bias
    correction folds into lr exactly like Adam.update does (host f64
    math imperative, traced scalars inside TrainStep)."""
    lrs, wds = [], []
    for i in indices:
        lr = optzr._get_lr(i)
        if kind == "adam":
            t = optzr._index_update_count[i]
            lr = lr * ((1. - optzr.beta2 ** t) ** 0.5
                       / (1. - optzr.beta1 ** t))
        lrs.append(lr)
        wds.append(optzr._get_wd(i))
    return lrs, wds


def _apply_bucket(optzr, kind, shapes, sizes, indices, weights, grads,
                  flat_grad, states, traced):
    """Update one bucket: ONE donated dispatch imperative, inline math
    traced.  ``states`` aligns with ``indices`` (per-param trees)."""
    from .optimizer import Optimizer
    mp = bool(optzr.multi_precision) and Optimizer._is_half(weights[0].dtype)
    has_mom = kind == "sgd" and bool(getattr(optzr, "momentum", 0.0))
    cfg = _static_cfg(optzr, kind)
    lrs, wds = _eff_lr_wd(optzr, kind, indices)
    n = len(indices)
    n_roles = len(_roles(kind, mp, has_mom))
    # role-major per-param state NDArrays (mirrors the executable layout)
    by_role = [[_role_arrays(kind, mp, has_mom, st)[r] for st in states]
               for r in range(n_roles)]

    if traced:
        offs, _ = _offsets(sizes)
        for i in range(n):
            if flat_grad is not None:
                g = flat_grad[offs[i]:offs[i] + sizes[i]].reshape(shapes[i])
            else:
                g = grads[i]._data
            sts = [by_role[r][i]._data for r in range(n_roles)]
            new_w, outs = _param_update(
                kind, mp, has_mom, cfg, weights[i]._data, g, sts,
                lrs[i], wds[i], optzr.rescale_grad,
                getattr(optzr, "momentum", None))
            weights[i]._set_data(new_w)
            for r in range(n_roles):
                by_role[r][i]._set_data(outs[r])
        return

    enabled = _ttrace._ENABLED
    t0 = _time.perf_counter_ns() if enabled else 0
    fn = _get_exec(kind, mp, has_mom, shapes, sizes, weights[0].dtype, cfg,
                   flat_grad is not None)
    args = [w._data for w in weights]
    if flat_grad is not None:
        dev = getattr(weights[0]._data, "device", None)
        if dev is not None and getattr(flat_grad, "device", None) != dev:
            import jax
            flat_grad = jax.device_put(flat_grad, dev)
        args.append(flat_grad)
    else:
        args += [g._data for g in grads]
    for role in by_role:
        args += [s._data for s in role]
    args += [_np.float32(lr) for lr in lrs]
    args += [_np.float32(wd) for wd in wds]
    args.append(_np.float32(optzr.rescale_grad))
    if has_mom:
        args.append(_np.float32(optzr.momentum))
    outs = fn(*args)
    for i in range(n):
        weights[i]._set_data(outs[i])
    for r in range(n_roles):
        for i in range(n):
            by_role[r][i]._set_data(outs[n + r * n + i])
    if enabled:
        _M_FUSED_BUCKETS.inc()
        _M_FUSED_PARAMS.inc(n)
        _M_BUCKET_SECONDS.observe((_time.perf_counter_ns() - t0) / 1e9)


def fused_update(optzr, indices, weights, grads, states, traced=False):
    """Multi-tensor fused update from per-param gradients: plan dtype
    buckets, one donated jitted dispatch per bucket.  The states list
    aligns with indices (per-param trees from Updater._ensure_state)."""
    kind = supported_kind(optzr)
    if kind is None:
        raise RuntimeError(f"optimizer_fusion does not support "
                           f"{type(optzr).__name__}")
    for i in indices:
        optzr._update_count(i)
    signature = tuple((tuple(w.shape), str(w.dtype), 1) for w in weights)
    buckets = planner().plan(signature)
    for b in buckets:
        pos = b.positions
        _apply_bucket(optzr, kind, b.shapes, b.sizes,
                      [indices[p] for p in pos],
                      [weights[p] for p in pos],
                      [grads[p] for p in pos], None,
                      [states[p] for p in pos], traced)


def fused_update_flat(optzr, indices, weights, states, shapes, sizes,
                      flat_grad, traced=False):
    """One bucket whose reduced gradients arrive as a single flat buffer
    straight off the fused allreduce wire (KVStoreLocal.pushpull_flat) —
    the flat buffer feeds the donated update directly (the executable
    slices it per segment), skipping the unflatten/reflatten HBM round
    trip."""
    kind = supported_kind(optzr)
    if kind is None:
        raise RuntimeError(f"optimizer_fusion does not support "
                           f"{type(optzr).__name__}")
    for i in indices:
        optzr._update_count(i)
    _apply_bucket(optzr, kind, tuple(tuple(s) for s in shapes),
                  tuple(sizes), indices, weights, None, flat_grad,
                  states, traced)


def record_fallback(n_params):
    """Parameters the caller routed per-key (sparse / unsupported)."""
    if n_params:
        _M_FALLBACK_PARAMS.inc(n_params)


def record_update():
    """One replica step took the bucketed path (Trainer counts this once
    per replica — fused_update/fused_update_flat can run several times
    within one step, so they must not self-count)."""
    _M_FUSED_UPDATES.inc()


# -- TrainStep integration ---------------------------------------------------

def plan_trainstep(optzr, trainable):
    """Bucket plan for a TrainStep's trainable params, computed at resolve
    time (host side, before any tracing).  Returns (kind, plan) with
    plan = [(bucket, positions)], or None when fusion is off or the
    optimizer is unsupported."""
    if not trainable or not fusion_active(optzr):
        return None
    kind = supported_kind(optzr)
    signature = tuple((tuple(p.data().shape), str(p.data().dtype), 1)
                      for p in trainable)
    buckets = planner().plan(signature)
    return kind, [(b, list(b.positions)) for b in buckets]


def traced_update(optzr, kind, plan, trainable, states):
    """The in-trace fused update TrainStep's raw() body calls instead of
    the per-param update_multi_precision loop.  Same math as the
    imperative executables."""
    for b, pos in plan:
        _apply_bucket(optzr, kind, b.shapes, b.sizes, pos,
                      [trainable[p]._data for p in pos],
                      [trainable[p]._data._grad for p in pos], None,
                      [states[p] for p in pos], True)
