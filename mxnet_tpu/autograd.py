"""Autograd: tape-based imperative differentiation.

Rebuild of the reference's N4 (src/imperative/imperative.cc ::
Imperative::RecordOp / Imperative::Backward) + python/mxnet/autograd.py.

Reference design: recording appends nnvm nodes to a tape; Backward builds a
graph, applies the nnvm ``Gradient`` pass (each op's FGradient), and interprets
it.  TPU-native design: recording captures a **concrete jax.vjp closure per
dispatched op** (residuals stored at forward time, so backward never re-runs
forward), and ``backward()`` walks the tape in reverse accumulating cotangents.
``create_graph=True`` (higher-order grad) re-enters the normal dispatch path
with each stored vjp closure treated as an op, so second-and-higher derivatives
are recorded tapes like any other compute.

Public API parity: ``record/pause/train_mode/predict_mode`` scopes,
``is_recording/is_training``, ``backward``, ``grad``, ``Function`` (custom py
autograd, reference c_api_function.cc / autograd.py :: Function),
``get_symbol`` is NOT provided (symbolic tape export is CachedOp's job here).
"""

from __future__ import annotations

import contextlib
import threading

import numpy as _np

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "set_recording", "set_training", "backward", "grad",
           "Function", "mark_variables"]

_tls = threading.local()


def _st():
    if not hasattr(_tls, "recording"):
        _tls.recording = False
        _tls.training = False
        _tls.tape = []
        _tls.session_depth = 0  # nesting depth of record() scopes
        _tls.create_graph_mode = False
    return _tls


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(flag):
    s = _st()
    prev, s.recording = s.recording, flag
    return prev


def set_training(flag):
    s = _st()
    prev, s.training = s.training, flag
    return prev


@contextlib.contextmanager
def _scope(recording=None, training=None):
    s = _st()
    prev_r, prev_t = s.recording, s.training
    entered_session = False
    if recording is not None:
        if recording:
            # only a truly outermost record session (not one nested under an
            # active-but-paused session) starts a fresh tape
            if s.session_depth == 0:
                s.tape = []
            s.session_depth += 1
            entered_session = True
        s.recording = recording
    if training is not None:
        s.training = training
    try:
        yield
    finally:
        s.recording, s.training = prev_r, prev_t
        if entered_session:
            s.session_depth -= 1


def record(train_mode=True):
    """``with autograd.record():`` — turn on recording (+train mode)."""
    return _scope(recording=True, training=train_mode)


def pause(train_mode=False):
    return _scope(recording=False, training=train_mode)


def train_mode():
    return _scope(training=True)


def predict_mode():
    return _scope(training=False)


# --------------------------------------------------------------------------
# tape
# --------------------------------------------------------------------------

_backward_epoch = 0


def _current_epoch():
    return _backward_epoch


class _Node:
    """One recorded op application."""
    __slots__ = ("op_name", "vjp_fn", "in_entries", "out_avals", "grads",
                 "op", "attrs", "inputs")

    def __init__(self, op_name, vjp_fn, in_entries, out_avals,
                 op=None, attrs=None, inputs=None):
        self.op_name = op_name
        self.vjp_fn = vjp_fn
        self.in_entries = in_entries  # per input: ("node", node, idx) | ("leaf", nd) | None
        self.out_avals = out_avals    # [(shape, dtype)] per output
        self.grads = None             # cotangent accumulation during backward
        # retained for create_graph=True (higher-order): re-derive the vjp
        # from the op's fn at the recorded inputs so the backward ops land on
        # the tape *connected to the original inputs*
        self.op = op
        self.attrs = attrs
        self.inputs = inputs


def _entries_for(inputs):
    from .ndarray import ndarray as _nd
    in_entries = []
    for a in inputs:
        if isinstance(a, _nd.NDArray):
            node = a._node
            if node is not None:
                in_entries.append(("node", node[0], node[1]))
            elif a._grad is not None:
                in_entries.append(("leaf", a))
            else:
                in_entries.append(None)
        else:
            in_entries.append(None)
    return in_entries


def _record(op, vjp_fn, inputs, outputs, attrs=None):
    """Called by ops.registry.invoke after a recorded dispatch."""
    s = _st()
    out_avals = [(o.shape, o.dtype) for o in outputs]
    node = _Node(op.name, vjp_fn, _entries_for(inputs), out_avals,
                 op=op, attrs=dict(attrs) if attrs else {}, inputs=list(inputs))
    s.tape.append(node)
    for i, o in enumerate(outputs):
        o._node = (node, i)
    return node


def _zeros_like_aval(aval):
    import jax.numpy as jnp
    shape, dtype = aval
    return jnp.zeros(shape, dtype)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True,
             create_graph=False):
    """Run backward from ``heads``; leaf ``.grad`` buffers are filled.

    Reference: MXAutogradBackwardEx → Imperative::Backward.
    """
    from .ndarray import ndarray as _nd
    if isinstance(heads, _nd.NDArray):
        heads = [heads]
        if head_grads is not None and isinstance(head_grads, _nd.NDArray):
            head_grads = [head_grads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    if len(head_grads) != len(heads):
        raise MXNetError("heads and head_grads length mismatch")

    s = _st()
    import jax.numpy as jnp
    from .ndarray import ndarray as _nd

    global _backward_epoch
    _backward_epoch += 1
    s.create_graph_mode = create_graph

    def _mk_seed(h, hg):
        if hg is not None:
            return hg if create_graph else hg._data
        ones = jnp.ones(h.shape, h.dtype)
        return _nd.NDArray._from_data(ones) if create_graph else ones

    # seed cotangents
    any_node = False
    tape = s.tape
    for h, hg in zip(heads, head_grads):
        node = h._node
        if node is None:
            if h._grad is not None:
                # backward directly on a leaf: d leaf/d leaf = head grad
                h._accumulate_grad(_mk_seed(h, hg))
            continue
        any_node = True
        n, idx = node
        if n.vjp_fn is None and n.inputs is None:
            raise MXNetError(
                "cannot run backward twice through the same graph: the tape "
                "was freed by the previous backward() (pass retain_graph=True "
                "to keep it, matching the reference contract)")
        if n.grads is None:
            n.grads = [None] * len(n.out_avals)
        seed = _mk_seed(h, hg)
        n.grads[idx] = seed if n.grads[idx] is None else n.grads[idx] + seed
    if not any_node:
        s.create_graph_mode = False
        return

    try:
        with _scope(training=train_mode):
            if create_graph:
                # record the backward ops onto the SAME tape (no reset) so
                # higher-order chains stay connected through original nodes
                with _keep_tape_recording():
                    visited = _run_tape_backward(tape, create_graph=True)
            else:
                visited = _run_tape_backward(tape, create_graph=False)
    finally:
        s.create_graph_mode = False

    if not retain_graph and not create_graph:
        # free only the subgraph this backward visited: per-device losses
        # recorded on the same tape (the reference's multi-ctx idiom
        # ``for l in losses: l.backward()``) keep their own nodes alive
        for n in visited:
            n.vjp_fn = None  # free residuals
            n.inputs = None
        if s.tape is tape:
            s.tape = [n for n in tape if n not in visited]
    else:
        for n in tape:
            n.grads = None


@contextlib.contextmanager
def _keep_tape_recording():
    """Recording on, but never resetting the tape (used by create_graph)."""
    s = _st()
    prev_r = s.recording
    s.recording = True
    s.session_depth += 1
    try:
        yield
    finally:
        s.recording = prev_r
        s.session_depth -= 1


def _freed(node):
    return node.vjp_fn is None and node.inputs is None


def _run_tape_backward(tape, create_graph=False):
    visited = set()
    for n in reversed(tape):
        if n.grads is None or all(g is None for g in n.grads):
            continue
        if _freed(n):
            raise MXNetError(
                "cannot run backward through a subgraph already freed by a "
                "previous backward() (pass retain_graph=True to keep it)")
        visited.add(n)
        if create_graph:
            in_grads = _recorded_vjp_call(n)
        else:
            cts = tuple(_coerce_ct(g, av) if g is not None
                        else _zeros_like_aval(av)
                        for g, av in zip(n.grads, n.out_avals))
            in_grads = n.vjp_fn(cts[0] if len(cts) == 1 else cts)
        for entry, g in zip(n.in_entries, in_grads):
            if entry is None or g is None:
                continue
            gd = g._data if hasattr(g, "_data") else g
            if getattr(gd, "dtype", None) is not None:
                import jax
                if gd.dtype == jax.dtypes.float0:
                    # gradient w.r.t. an integer-valued input (indices,
                    # lengths): carries no information and float0 supports
                    # no arithmetic — drop instead of accumulating
                    continue
            kind = entry[0]
            if kind == "leaf":
                entry[1]._accumulate_grad(g)
            else:  # ("node", node, idx)
                _, pnode, pidx = entry
                if _freed(pnode):
                    # the producer was freed by an earlier backward (it may
                    # even be off the tape): silent gradient loss otherwise
                    raise MXNetError(
                        "cannot run backward: a shared subgraph was freed by "
                        "a previous backward() (pass retain_graph=True, or "
                        "call backward once on the combined heads)")
                if pnode.grads is None:
                    pnode.grads = [None] * len(pnode.out_avals)
                pnode.grads[pidx] = (g if pnode.grads[pidx] is None
                                     else pnode.grads[pidx] + g)
        n.grads = None
    return visited


def _coerce_ct(g, aval):
    """Cast a cotangent to its primal output's dtype.

    Mixed-precision tapes (mx.amp) legitimately produce f32 cotangents for
    bf16 primal outputs (downstream ops upcast); jax.vjp requires exact
    dtype match, so coerce here — the reference's backward does the same
    implicitly through amp_cast nodes in the grad graph."""
    _, want_dtype = aval
    data = g._data if hasattr(g, "_data") else g
    import jax
    import numpy as _onp
    want = _onp.dtype(want_dtype)
    want_float = want.kind == "f" or want.name == "bfloat16"
    if data.dtype == jax.dtypes.float0 or not want_float:
        # integer-valued primal outputs take float0 cotangents — never cast
        return g
    if data.dtype != want_dtype:
        cast = data.astype(want_dtype)
        if hasattr(g, "_data"):
            from .ndarray import ndarray as _nd
            return _nd.NDArray._from_data(cast)
        return cast
    return g


def _recorded_vjp_call(node):
    """create_graph=True: replay the op's vjp as a *recorded* op whose inputs
    are the original forward inputs plus the cotangents, so the backward ops
    land on the tape connected to the original leaves (higher-order grads).

    Falls back to the stored closure (disconnected, first-order only) for
    nodes without a replayable op (custom autograd.Function)."""
    from .ops import registry as _reg
    from .ndarray import ndarray as _nd
    import jax

    cts = [_coerce_ct(g, av) if g is not None else
           _nd.NDArray._from_data(_zeros_like_aval(av))
           for g, av in zip(node.grads, node.out_avals)]

    if node.op is None or node.inputs is None:
        ct_raw = tuple(c._data for c in cts)
        return node.vjp_fn(ct_raw[0] if len(ct_raw) == 1 else ct_raw)

    fwd_inputs = [a for a in node.inputs]
    n_in = len(fwd_inputs)
    op, attrs = node.op, node.attrs

    def replay(*args, **kw):
        ins, ct = args[:n_in], args[n_in:]
        f = _reg._callable_for(op, kw)
        _, vjp = jax.vjp(f, *ins)
        res = vjp(ct[0] if len(ct) == 1 else tuple(ct))
        return res if len(res) > 1 else res[0]

    g_op = _reg.Op(f"_backward_{node.op_name}", replay,
                   num_outputs=n_in if n_in > 1 else 1, jit=False)
    res = _reg.invoke(g_op, fwd_inputs + cts, attrs)
    if not isinstance(res, list):
        res = [res]
    return res


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """autograd.grad — return grads of heads wrt variables (not into .grad).

    Reference: python/mxnet/autograd.py :: grad (MXAutogradBackwardEx with
    variable handles).
    """
    from .ndarray import ndarray as _nd
    single_var = isinstance(variables, _nd.NDArray)
    if single_var:
        variables = [variables]
    if retain_graph is None:
        retain_graph = create_graph

    # temporarily give each variable a fresh grad buffer marked 'add'
    saved = [(v._grad, v.grad_req) for v in variables]
    for v in variables:
        v._grad = _nd.zeros(v.shape, dtype=v.dtype, ctx=v.ctx)
        v.grad_req = "add"
    try:
        backward(heads, head_grads, retain_graph=retain_graph,
                 train_mode=train_mode, create_graph=create_graph)
        out = []
        for v in variables:
            if v._grad_epoch != _backward_epoch:
                raise MXNetError(
                    "cannot differentiate with respect to a variable that "
                    "the recorded graph does not reach (reference contract: "
                    "MXAutogradBackwardEx errors on unreachable variables)")
            out.append(v._grad)
    finally:
        for v, (og, oreq) in zip(variables, saved):
            v._grad, v.grad_req = og, oreq
    return out[0] if single_var else out


def mark_variables(variables, gradients, grad_reqs="write"):
    """Reference API: attach grad buffers to arrays (used by Module path)."""
    from .ndarray import ndarray as _nd
    if isinstance(variables, _nd.NDArray):
        variables, gradients = [variables], [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, r in zip(variables, gradients, grad_reqs):
        v._grad = g
        v.grad_req = r


# --------------------------------------------------------------------------
# custom Function (reference: autograd.py :: Function + c_api_function.cc)
# --------------------------------------------------------------------------

class Function:
    """User-defined differentiable function.

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` operating on NDArrays.  Parity with the
    reference's ``mx.autograd.Function`` (which trampolines through the C API);
    here it is a tape node whose vjp calls the user's ``backward`` in pause().
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import ndarray as _nd
        s = _st()
        rec = s.recording
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if rec:
            func = self

            def vjp_fn(cts):
                if not isinstance(cts, tuple):
                    cts = (cts,)
                with pause():
                    ct_nds = [_nd.NDArray._from_data(c) for c in cts]
                    igs = func.backward(*ct_nds)
                if isinstance(igs, _nd.NDArray):
                    igs = [igs]
                return [g._data if isinstance(g, _nd.NDArray) else g for g in igs]

            node = _Node(type(self).__name__, vjp_fn,
                         in_entries=[None] * len(inputs),
                         out_avals=[(o.shape, o.dtype) for o in outs])
            # fill input entries like _record does
            entries = []
            for a in inputs:
                if isinstance(a, _nd.NDArray):
                    if a._node is not None:
                        entries.append(("node", a._node[0], a._node[1]))
                    elif a._grad is not None:
                        entries.append(("leaf", a))
                    else:
                        entries.append(None)
                else:
                    entries.append(None)
            node.in_entries = entries
            s.tape.append(node)
            for i, o in enumerate(outs):
                o._node = (node, i)
        return outs[0] if single else tuple(outs)
