"""Step-time attribution — where did this training step's wall time go?

The profiling recipes in PROFILE.md all end with the same question: is the
run input-bound, comms-bound, or compute-bound?  ``StepClock`` answers it
continuously: instrumented chokepoints split every optimizer step into

- ``data_wait``  — blocking on the input pipeline (DataLoader batch fetch,
  noted between steps and folded into the step they fed);
- ``h2d``        — host→device transfer of the batch and state
  (``parallel.TrainStep``'s ``device_put`` block);
- ``compute``    — forward/backward/dispatch; also absorbs all
  *unattributed* step time (user code between steps), so the five phases
  always sum to the step's wall time;
- ``comms``      — gradient reduction (``trainer.allreduce``, which wraps
  the kvstore pushpull / fused psum path);
- ``optimizer``  — the weight update.

``gluon.Trainer.step`` and ``parallel.TrainStep`` drive the process-global
``STEP_CLOCK`` whenever telemetry is enabled (callers gate on the tracer
flag — this module reads no flags itself, keeping graftcheck GC05 happy).
Every finished step observes into the ``mxnet_step_phase_seconds`` labeled
histograms and a bounded rolling window (``MXNET_STEPCLOCK_WINDOW``) from
which :func:`StepClock.summary` computes per-phase medians and the rolling
**verdict**: ``input-bound`` (data_wait + h2d dominate), ``comms-bound``,
or ``compute-bound`` (compute + optimizer).  ``telemetry.report()`` renders
the table; ``tools/telemetry_report.py`` renders it per rank from exported
snapshots.

A ``TrainStep`` "step" is one jitted dispatch — with ``run(steps=K)`` that
is K fused steps, so phase times are per *dispatch*; the verdict is
unaffected (it compares shares, not absolutes).

Stdlib-only; nothing here imports jax.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from .. import config
from . import metrics as _metrics

__all__ = ["PHASES", "StepClock", "STEP_CLOCK", "report"]

PHASES = ("data_wait", "h2d", "compute", "comms", "optimizer")

# verdict label -> the phases whose medians it aggregates
VERDICT_GROUPS = {
    "input-bound": ("data_wait", "h2d"),
    "comms-bound": ("comms",),
    "compute-bound": ("compute", "optimizer"),
}

_PHASE_HIST = {
    p: _metrics.histogram(
        "mxnet_step_phase_seconds",
        "Per-step wall seconds attributed to each phase of the training "
        "step (data_wait/h2d/compute/comms/optimizer).",
        labels={"phase": p})
    for p in PHASES
}


def _pct(sorted_vals, q):
    """Nearest-rank percentile of an ascending list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[idx]


class _PhaseTimer:
    """``with clock.phase("h2d"): ...`` convenience for user code."""

    __slots__ = ("_clock", "_name", "_t0")

    def __init__(self, clock, name):
        self._clock = clock
        self._name = name
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._clock.note(self._name, time.perf_counter() - self._t0)
        return False


class StepClock:
    """Rolling per-step phase accumulator (module docstring has the full
    story).  Thread-safe: phase notes may arrive from the consumer thread
    (Trainer), the DataLoader iterator, or a pipeline assembler."""

    def __init__(self, window=None):
        if window is None:
            window = config.get_int("MXNET_STEPCLOCK_WINDOW", 64)
        self._lock = threading.Lock()
        self._window = deque(maxlen=max(2, int(window)))
        self._pending: dict = {}   # notes landing between steps (data_wait)
        self._cur = None           # open step's phase accumulation
        self._t_begin = None
        self._last_end = None      # end of the previous step (gap origin)
        self._gap = 0.0

    # -- feeding -----------------------------------------------------------

    def begin_step(self):
        """Open a step: fold pending between-step notes in and anchor the
        gap since the previous step's end (forward/backward/user code —
        attributed to compute unless noted otherwise)."""
        now = time.perf_counter()
        with self._lock:
            self._gap = (now - self._last_end) \
                if self._last_end is not None else 0.0
            self._cur = dict(self._pending)
            self._pending.clear()
            self._t_begin = now

    def note(self, phase, seconds):
        """Attribute ``seconds`` to ``phase`` — into the open step, or the
        pending pool if none is open (a DataLoader fetch between steps)."""
        if phase not in PHASES:
            raise ValueError(f"unknown step phase {phase!r}; "
                             f"phases are {PHASES}")
        with self._lock:
            tgt = self._cur if self._cur is not None else self._pending
            tgt[phase] = tgt.get(phase, 0.0) + float(seconds)

    def phase(self, name):
        """Context manager noting its body's duration under ``name``."""
        if name not in PHASES:
            raise ValueError(f"unknown step phase {name!r}; "
                             f"phases are {PHASES}")
        return _PhaseTimer(self, name)

    def end_step(self):
        """Close the open step: unattributed time goes to compute, the
        record joins the rolling window, and each phase observes into its
        ``mxnet_step_phase_seconds`` histogram."""
        now = time.perf_counter()
        with self._lock:
            if self._t_begin is None:
                return          # begin_step never ran (or step abandoned)
            cur, self._cur = self._cur or {}, None
            total = (now - self._t_begin) + self._gap
            noted = sum(cur.values())
            cur["compute"] = cur.get("compute", 0.0) \
                + max(0.0, total - noted)
            rec = {p: cur.get(p, 0.0) for p in PHASES}
            # noted phases can exceed the measured wall span (a fetch
            # timed on another thread overlapping the step): total always
            # covers the phases so shares stay <= 100%
            rec["total"] = max(total, sum(rec[p] for p in PHASES))
            self._window.append(rec)
            self._last_end = now
            self._t_begin = None
            self._gap = 0.0
        for p in PHASES:
            _PHASE_HIST[p].observe(rec[p])

    # -- reading -----------------------------------------------------------

    @property
    def steps(self):
        with self._lock:
            return len(self._window)

    def summary(self):
        """{steps, phases: {name: {median, p90, mean}}, groups, verdict}
        over the rolling window; verdict 'idle' when no steps recorded."""
        with self._lock:
            recs = list(self._window)
        if not recs:
            return {"steps": 0, "phases": {}, "groups": {},
                    "verdict": "idle"}
        phases = {}
        for p in PHASES + ("total",):
            vals = sorted(r[p] for r in recs)
            phases[p] = {"median": _pct(vals, 0.5), "p90": _pct(vals, 0.9),
                         "mean": sum(vals) / len(vals)}
        groups = {label: sum(phases[p]["median"] for p in members)
                  for label, members in VERDICT_GROUPS.items()}
        verdict = max(groups, key=groups.get) \
            if any(groups.values()) else "compute-bound"
        return {"steps": len(recs), "phases": phases, "groups": groups,
                "verdict": verdict}

    def verdict(self):
        """The rolling bottleneck verdict: 'input-bound' / 'comms-bound' /
        'compute-bound' ('idle' with no recorded steps)."""
        return self.summary()["verdict"]

    def reset(self):
        with self._lock:
            self._window.clear()
            self._pending.clear()
            self._cur = None
            self._t_begin = None
            self._last_end = None
            self._gap = 0.0


STEP_CLOCK = StepClock()


def report(clock=None, registry=None):
    """Human-readable attribution report: the per-phase table over the
    rolling window, the bottleneck verdict, and the headline run counters.
    This is what ``mx.telemetry.report()`` prints."""
    clock = clock if clock is not None else STEP_CLOCK
    registry = registry if registry is not None else _metrics.REGISTRY
    s = clock.summary()
    lines = [f"step-time attribution (last {s['steps']} step(s)):"]
    if not s["steps"]:
        lines.append("  (no steps recorded — enable telemetry "
                     "[MXNET_TELEMETRY=1] and run training steps)")
        return "\n".join(lines)
    total_med = s["phases"]["total"]["median"] or 1e-12
    lines.append(f"  {'phase':<10} {'median_ms':>10} {'p90_ms':>10} "
                 f"{'mean_ms':>10} {'share':>7}")
    for p in PHASES + ("total",):
        ph = s["phases"][p]
        share = ph["median"] / total_med
        lines.append(
            f"  {p:<10} {ph['median'] * 1e3:>10.3f} {ph['p90'] * 1e3:>10.3f}"
            f" {ph['mean'] * 1e3:>10.3f} {share:>6.0%}")
    shares = {k: v / total_med for k, v in s["groups"].items()}
    lines.append(
        f"verdict: {s['verdict']} "
        f"(input {shares['input-bound']:.0%} / "
        f"comms {shares['comms-bound']:.0%} / "
        f"compute {shares['compute-bound']:.0%})")
    counters = []
    for name in ("mxnet_trainer_steps_total",
                 "mxnet_sharding_step_dispatches_total",
                 "mxnet_sharding_retraces_total",
                 "mxnet_op_dispatch_total",
                 "mxnet_dataloader_batches_total",
                 "mxnet_resilience_deadline_exceeded_total"):
        m = registry.get(name)
        if m is not None and m.value:
            counters.append(f"  {name} = {m.value}")
    if counters:
        lines.append("counters:")
        lines.extend(counters)
    return "\n".join(lines)
