"""Hardware-free perf-regression gate — committed analytic baselines.

Every perf claim since BENCH_r04 is parked in PROFILE.md because the
axon tunnel died; but the cost ledger (ISSUE 12) already computes flops,
bytes-accessed, donation-aware peak-HBM, executable counts and analytic
MFU per owned jit boundary with NO hardware — XLA's own AOT numbers on
the CPU backend.  This module turns that ledger into *enforced
invariants* (ROADMAP item 5, the ``autoshard_plan_golden.json`` pattern
applied to performance):

- **snapshot**: each registered lane builds its real workload (train
  step / serving engine / kvstore pushpull), arms the ledger, compiles,
  and runs a 2-iteration steady-state window with NO timing loop — the
  captured record is executables built, armed-jit dispatches per
  iteration, steady-state retraces (``analysis.runtime``'s compile
  counter), total flops, bytes-accessed, peak-HBM, deterministic
  analytic MFU, and the lane's key telemetry counters.  Everything in
  the record is a function of program structure, never of wall time, so
  two runs on any machine produce byte-identical JSON.
- **baseline**: ``tools/perfgate.py --write-baseline --reason "..."``
  serializes the snapshot sorted-keys/no-timestamps into the committed
  ``tests/perf_baseline.json`` with a content digest (hand edits are
  rejected) and an append-only reason log.
- **gate**: ``tools/perfgate.py --check`` re-snapshots and diffs against
  the committed file under per-metric tolerance bands — exact for
  dispatches/retraces/executables/counters, ±2% flops/bytes, ±5%
  peak-HBM — failing red on drift, added lanes, or removed lanes.

Determinism contract: ``analytic_mfu`` is the roofline MFU *bound*
(arithmetic intensity vs the machine ridge) and ``analytic_step_s`` is
``max(flops/peak_flops, bytes/peak_bw)`` — both pure functions of the
compiled program and the (env-pinnable) chip peaks.  Wall-clock readings
ride each fresh snapshot under ``observed`` for the on-chip sweep
(tools/onchip_sweep.py) but are STRIPPED before serialization.

Import-time this module is jax-free (the ``telemetry_report`` standalone
-load contract): lane runners import jax lazily and only execute in the
snapshot child processes.
"""

from __future__ import annotations

import hashlib
import json
import os

from .. import config
from . import costmodel, metrics

__all__ = [
    "BaselineError", "LANES", "METRIC_TOLERANCES", "SITE_TOLERANCES",
    "SCHEMA_VERSION", "canonical_doc", "canonical_lanes", "default_baseline_path",
    "diff_snapshots", "lane_names", "lanes_digest", "live_delta",
    "load_baseline", "report_lines", "run_lane", "validate_baseline",
]

SCHEMA_VERSION = 1

# -- tolerance bands ---------------------------------------------------------
# None  -> exact string equality (verdicts)
# 0.0   -> exact numeric equality (structural counts: any drift is a real
#          program-shape change and must be re-baselined deliberately)
# r > 0 -> relative band: |got - base| / max(|base|, 1e-9) <= r
#          (XLA cost/memory analysis jitters slightly across versions)
METRIC_TOLERANCES = {
    "dispatches_per_step": 0.0,
    "executables": 0.0,
    "retraces_steady": 0.0,
    "flops": 0.02,
    "bytes_accessed": 0.02,
    "peak_hbm_bytes": 0.05,
    "analytic_mfu": 0.02,
    "analytic_step_s": 0.02,
    "verdict": None,
}
SITE_TOLERANCES = {
    "executables": 0.0,
    "calls": 0.0,
    "flops": 0.02,
    "bytes_accessed": 0.02,
    "peak_bytes": 0.05,
}
_VOLATILE_KEYS = ("observed",)     # wall-time block: never serialized


def default_baseline_path():
    """The committed baseline path; ``MXNET_PERFGATE_BASELINE`` overrides
    (tests, side-by-side baselines for a hardware tier)."""
    p = config.get("MXNET_PERFGATE_BASELINE")
    if p:
        return p
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo, "tests", "perf_baseline.json")


# -- canonical serialization + digest ----------------------------------------

def canonical_lanes(lanes):
    """Deep-copy with volatile (wall-clock) blocks stripped — the exact
    dict that gets digested and serialized."""
    out = {}
    for name in sorted(lanes):
        rec = {k: v for k, v in lanes[name].items()
               if k not in _VOLATILE_KEYS}
        out[name] = json.loads(json.dumps(rec, sort_keys=True))
    return out


def lanes_digest(lanes):
    blob = json.dumps(canonical_lanes(lanes), sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def canonical_doc(lanes, reasons):
    """The full baseline document, ready for byte-stable serialization."""
    lanes = canonical_lanes(lanes)
    return {
        "schema": SCHEMA_VERSION,
        "digest": lanes_digest(lanes),
        "reasons": list(reasons),
        "lanes": lanes,
    }


def dump_doc(doc):
    """Byte-deterministic text form: sorted keys, fixed indent, trailing
    newline, no timestamps anywhere."""
    return json.dumps(doc, sort_keys=True, indent=1) + "\n"


class BaselineError(ValueError):
    """Raised on a missing/corrupt/hand-edited baseline file."""


def validate_baseline(doc, path="<baseline>"):
    if not isinstance(doc, dict):
        raise BaselineError(f"{path}: baseline must be a JSON object")
    if doc.get("schema") != SCHEMA_VERSION:
        raise BaselineError(
            f"{path}: schema {doc.get('schema')!r} != {SCHEMA_VERSION} "
            "(regenerate with tools/perfgate.py --write-baseline)")
    lanes = doc.get("lanes")
    if not isinstance(lanes, dict) or not lanes:
        raise BaselineError(f"{path}: no lanes recorded")
    want = lanes_digest(lanes)
    if doc.get("digest") != want:
        raise BaselineError(
            f"{path}: content digest mismatch (file says "
            f"{str(doc.get('digest'))[:12]}…, lanes hash to {want[:12]}…) "
            "— the baseline was hand-edited; regenerate it with "
            "tools/perfgate.py --write-baseline --reason '...'")
    for name, rec in lanes.items():
        m = rec.get("metrics")
        if not isinstance(m, dict):
            raise BaselineError(f"{path}: lane {name!r} has no metrics block")
        missing = [k for k in METRIC_TOLERANCES if k not in m]
        if missing:
            raise BaselineError(
                f"{path}: lane {name!r} missing metrics {missing}")
    return doc


def load_baseline(path):
    if not os.path.exists(path):
        raise BaselineError(f"{path}: no committed baseline "
                            "(tools/perfgate.py --write-baseline creates it)")
    try:
        with open(path) as f:
            doc = json.load(f)
    except ValueError as e:
        raise BaselineError(f"{path}: not valid JSON ({e})") from e
    return validate_baseline(doc, path=path)


# -- the diff engine ---------------------------------------------------------

def _check_value(metric, base, got, tol):
    """None when within band, else a failure dict."""
    if tol is None:
        if str(base) != str(got):
            return {"metric": metric, "base": base, "got": got,
                    "tol": "exact"}
        return None
    try:
        b, g = float(base), float(got)
    except (TypeError, ValueError):
        return {"metric": metric, "base": base, "got": got,
                "tol": "exact", "error": "non-numeric"}
    if b == g:
        return None
    rel = abs(g - b) / max(abs(b), 1e-9)
    if tol == 0.0 or rel > tol:
        return {"metric": metric, "base": base, "got": got, "tol": tol,
                "rel": round(rel, 6)}
    return None


def _diff_block(prefix, base, got, tols, fails, exact_keys=False):
    keys = set(base) | set(got)
    for k in sorted(keys):
        if k not in base:
            fails.append({"metric": f"{prefix}{k}", "base": None,
                          "got": got[k], "tol": "exact"})
            continue
        if k not in got:
            fails.append({"metric": f"{prefix}{k}", "base": base[k],
                          "got": None, "tol": "exact"})
            continue
        tol = 0.0 if exact_keys else tols.get(k, 0.0)
        f = _check_value(f"{prefix}{k}", base[k], got[k], tol)
        if f:
            fails.append(f)


def diff_lane(base, fresh):
    """One lane's failure list (empty = within every band)."""
    fails: list = []
    if base.get("config") != fresh.get("config"):
        fails.append({"metric": "config", "base": base.get("config"),
                      "got": fresh.get("config"), "tol": "exact"})
    _diff_block("", base.get("metrics") or {}, fresh.get("metrics") or {},
                METRIC_TOLERANCES, fails)
    _diff_block("counters.", base.get("counters") or {},
                fresh.get("counters") or {}, {}, fails, exact_keys=True)
    bsites, fsites = base.get("sites") or {}, fresh.get("sites") or {}
    for site in sorted(set(bsites) | set(fsites)):
        if site not in bsites or site not in fsites:
            fails.append({"metric": f"sites.{site}",
                          "base": "present" if site in bsites else None,
                          "got": "present" if site in fsites else None,
                          "tol": "exact"})
            continue
        _diff_block(f"sites.{site}.", bsites[site], fsites[site],
                    SITE_TOLERANCES, fails)
    return fails


def diff_snapshots(baseline_lanes, fresh_lanes):
    """Full gate verdict: per-lane ok/drift plus loud added/removed."""
    baseline_lanes = canonical_lanes(baseline_lanes)
    fresh_lanes = canonical_lanes(fresh_lanes)
    report = {"ok": True, "lanes": {}, "added": [], "removed": []}
    for name in sorted(set(baseline_lanes) | set(fresh_lanes)):
        if name not in baseline_lanes:
            report["added"].append(name)
            report["lanes"][name] = {
                "verdict": "added", "failures": [
                    {"metric": "lane", "base": None, "got": "present",
                     "tol": "exact"}]}
            report["ok"] = False
            continue
        if name not in fresh_lanes:
            report["removed"].append(name)
            report["lanes"][name] = {
                "verdict": "removed", "failures": [
                    {"metric": "lane", "base": "present", "got": None,
                     "tol": "exact"}]}
            report["ok"] = False
            continue
        fails = diff_lane(baseline_lanes[name], fresh_lanes[name])
        report["lanes"][name] = {"verdict": "drift" if fails else "ok",
                                 "failures": fails}
        if fails:
            report["ok"] = False
    return report


def live_delta(baseline_doc, site_summary, counters=None):
    """Partial diff of a LIVE process against the committed baseline —
    the ``/perfgate.json`` endpoint and ``telemetry_report --perf-diff``.

    A live process runs one workload, not the whole lane matrix, so only
    the analytic per-site invariants that overlap are compared (flops /
    bytes / peak-HBM of each site's largest executable); call volumes and
    counters are workload-scaled and reported alongside, not gated."""
    live = {}
    for site, s in (site_summary or {}).items():
        live[site] = {"flops": float(s.get("flops") or 0.0),
                      "bytes_accessed": float(s.get("bytes_accessed") or 0.0),
                      "peak_bytes": int(s.get("peak_bytes") or 0)}
    out = {"ok": True, "baseline_digest": baseline_doc.get("digest"),
           "overlap_sites": 0, "lanes": {}}
    gated = {k: SITE_TOLERANCES[k]
             for k in ("flops", "bytes_accessed", "peak_bytes")}
    for name, rec in sorted((baseline_doc.get("lanes") or {}).items()):
        overlap = sorted(set(rec.get("sites") or {}) & set(live))
        if not overlap:
            out["lanes"][name] = {"verdict": "no-overlap", "failures": []}
            continue
        fails: list = []
        for site in overlap:
            base = {k: rec["sites"][site][k] for k in gated
                    if k in rec["sites"][site]}
            got = {k: live[site][k] for k in gated}
            _diff_block(f"sites.{site}.", base, got, gated, fails)
        out["overlap_sites"] += len(overlap)
        out["lanes"][name] = {"verdict": "drift" if fails else "ok",
                              "failures": fails}
        if fails:
            out["ok"] = False
    if counters:
        out["live_counters"] = {k: counters[k] for k in sorted(counters)}
    return out


def report_lines(report, baseline_path=None):
    """Human rendering of a :func:`diff_snapshots` report."""
    lines = []
    if baseline_path:
        lines.append(f"perfgate — baseline {baseline_path}")
    for name, lane in sorted(report["lanes"].items()):
        mark = {"ok": "OK  ", "drift": "DRIFT", "added": "ADDED",
                "removed": "GONE "}.get(lane["verdict"], "??")
        lines.append(f"  [{mark}] {name}")
        for f in lane["failures"][:12]:
            rel = f" (rel {f['rel']:+.2%})" if "rel" in f else ""
            lines.append(f"      {f['metric']}: baseline={f['base']!r} "
                         f"fresh={f['got']!r} tol={f['tol']}{rel}")
        extra = len(lane["failures"]) - 12
        if extra > 0:
            lines.append(f"      … and {extra} more")
    verdict = "PASS" if report["ok"] else "FAIL"
    n_bad = sum(1 for v in report["lanes"].values()
                if v["verdict"] != "ok")
    lines.append(f"perfgate verdict: {verdict} "
                 f"({len(report['lanes']) - n_bad}/{len(report['lanes'])} "
                 "lanes within tolerance)")
    return lines


# -- snapshot capture (lane runners; jax only in child processes) ------------

def _begin_capture():
    """Arm telemetry + the cost ledger from a clean slate (bench.py's
    ``_telemetry_on`` contract) BEFORE the lane compiles, so every
    executable build lands in the ledger."""
    from . import tracer
    tracer.enable()
    costmodel.arm()
    from . import clear as _clear
    _clear()
    metrics.REGISTRY.reset()


def _total_armed_calls():
    return sum(costmodel.LEDGER._call_counts().values())


def _metric_value(name):
    """Counter value / histogram observation count for a live metric; 0
    when the metric never registered."""
    m = metrics.REGISTRY.get(name)
    if m is None:
        return 0
    v = getattr(m, "value", None)
    if v is None:
        v = getattr(m, "count", 0)
    return v


def _counter_block(names):
    out = {}
    for n in names:
        m = metrics.REGISTRY.get(n)
        if m is None:
            out[n] = 0
        elif hasattr(m, "value"):
            v = float(m.value)
            out[n] = int(v) if v.is_integer() else round(v, 6)
        else:                       # histogram: structural count + sum
            out[n + "_count"] = int(m.count)
            s = float(m.sum)
            out[n + "_sum"] = int(s) if s.is_integer() else round(s, 6)
    return out


def _steady_capture(fn, iters, extra_dispatch_counters=()):
    """Run the already-compiled steady-state iteration ``iters`` times,
    counting armed-jit dispatches, backend compiles (retraces), and any
    lane-specific dispatch counters.  No host syncs in the window — the
    wall reading is informational and the caller drains afterwards."""
    import time
    from ..analysis import runtime as _art
    calls0 = _total_armed_calls()
    extra0 = sum(_metric_value(n) for n in extra_dispatch_counters)
    compiles0 = _art.compile_count()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    wall = time.perf_counter() - t0
    dispatches = (_total_armed_calls() - calls0
                  + sum(_metric_value(n) for n in extra_dispatch_counters)
                  - extra0)
    return {"dispatches": dispatches,
            "retraces": _art.compile_count() - compiles0,
            "wall_s": wall, "iters": iters}


def _site_rollup():
    sites = {}
    for site, s in sorted(costmodel.LEDGER.site_summary().items()):
        sites[site] = {
            "executables": int(s["executables"]),
            "calls": int(s["calls"]),
            "flops": int(round(s["flops"])),
            "bytes_accessed": int(round(s["bytes_accessed"])),
            "peak_bytes": int(s["peak_bytes"]),
        }
    return sites


def _finish_record(cfg, primary_site, steady, steps_per_iter=1,
                   counter_names=(), dtype="float32"):
    """Assemble one lane's record from the armed ledger + registry.

    ``analytic_step_s`` / ``analytic_mfu`` are pure functions of the
    compiled program and the chip peaks (roofline bound — NOT wall
    time), so the record is byte-deterministic; the wall reading rides
    separately under ``observed`` and never reaches the baseline."""
    ents = costmodel.LEDGER.entries()
    good = [e for e in ents if not e.get("error")]
    prim = [e for e in good if e["site"] == primary_site]
    if prim:
        top = max(prim, key=lambda e: e.get("flops") or 0.0)
        flops = float(top.get("flops") or 0.0)
        byts = float(top.get("bytes_accessed") or 0.0)
    else:
        flops = byts = 0.0
    peak_hbm = max([int(e.get("peak_bytes", 0) or 0) for e in good] or [0])
    pf = costmodel.peak_flops(dtype)
    pb = costmodel.peak_hbm_bytes_per_s()
    rl = costmodel.roofline(flops, byts, dtype=dtype)
    step_s = max(flops / pf, byts / pb)
    per_step_wall = steady["wall_s"] / max(steady["iters"] * steps_per_iter, 1)
    record = {
        "config": dict(cfg, primary_site=primary_site,
                       steps_per_iter=steps_per_iter,
                       steady_iters=steady["iters"]),
        "metrics": {
            "dispatches_per_step": round(
                steady["dispatches"] / max(steady["iters"], 1), 4),
            "executables": len(ents),
            "retraces_steady": int(steady["retraces"]),
            "flops": int(round(flops)),
            "bytes_accessed": int(round(byts)),
            "peak_hbm_bytes": int(peak_hbm),
            "analytic_mfu": rl["roofline_mfu_bound"],
            "analytic_step_s": round(step_s, 9),
            "verdict": rl["verdict"] if flops else "no-entries",
        },
        "sites": _site_rollup(),
        "counters": _counter_block(counter_names),
        "observed": {
            "steady_wall_s": round(steady["wall_s"], 6),
            "wall_s_per_step": round(per_step_wall, 6),
            "measured_mfu": round(flops / max(per_step_wall * pf, 1e-12), 6),
        },
    }
    return record


# -- lane implementations ----------------------------------------------------

def _bert_train_lane(batch, seq_len, scan_steps):
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd, parallel
    from mxnet_tpu.gluon.model_zoo import bert

    vocab = 30522
    mx.random.seed(0)
    np.random.seed(0)
    model = bert.bert_model("bert_3_128_2", vocab_size=vocab,
                            max_length=seq_len, dropout=0.0)
    model.initialize(mx.initializer.Normal(0.02))

    def loss_fn(out, labels):
        _, _, logits = out
        return mx.nd.softmax_cross_entropy(
            logits.reshape((-1, logits.shape[-1])).astype("float32"),
            labels.reshape((-1,))) / labels.size

    step = parallel.TrainStep(model, loss_fn,
                              mx.optimizer.Adam(learning_rate=1e-4),
                              mesh=parallel.make_mesh())
    r = np.random.RandomState(0)
    toks = nd.array(r.randint(0, vocab,
                              (scan_steps, batch, seq_len)).astype(np.int32))
    labs = nd.array(r.randint(0, vocab,
                              (scan_steps, batch, seq_len)).astype(np.int32))
    _begin_capture()
    losses = step.run(toks, labs)                     # compile + warmup
    float(np.asarray(losses.asnumpy()[-1]))
    steady = _steady_capture(lambda: step.run(toks, labs), iters=2)
    float(np.asarray(step.run(toks, labs).asnumpy()[-1]))   # drain
    return _finish_record(
        {"model": "bert_3_128_2", "batch": batch, "seq_len": seq_len,
         "scan_steps": scan_steps, "dtype": "float32"},
        "parallel.TrainStep", steady, steps_per_iter=scan_steps,
        counter_names=("mxnet_sharding_step_dispatches_total",
                       "mxnet_sharding_retraces_total"))


def _lane_bert_headline():
    return _bert_train_lane(batch=4, seq_len=32, scan_steps=2)


def _lane_bert_seq512():
    return _bert_train_lane(batch=2, seq_len=512, scan_steps=2)


def _lane_llama_longseq():
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd, parallel
    from mxnet_tpu.gluon.model_zoo.llama import LlamaModel

    vocab, batch, seq_len, scan_steps = 512, 1, 2048, 1
    mx.random.seed(0)
    np.random.seed(0)
    model = LlamaModel(vocab_size=vocab, num_layers=2, units=64, hidden=172,
                       heads=4, kv_heads=2, remat=False)
    model.initialize(mx.initializer.Normal(0.02))

    def loss_fn(out, labels):
        return mx.nd.softmax_cross_entropy(
            out.reshape((-1, out.shape[-1])).astype("float32"),
            labels.reshape((-1,))) / labels.size

    step = parallel.TrainStep(model, loss_fn,
                              mx.optimizer.Adam(learning_rate=1e-4),
                              mesh=parallel.make_mesh())
    r = np.random.RandomState(0)
    toks = nd.array(r.randint(0, vocab,
                              (scan_steps, batch, seq_len)).astype(np.int32))
    labs = nd.array(r.randint(0, vocab,
                              (scan_steps, batch, seq_len)).astype(np.int32))
    _begin_capture()
    losses = step.run(toks, labs)
    float(np.asarray(losses.asnumpy()[-1]))
    steady = _steady_capture(lambda: step.run(toks, labs), iters=2)
    float(np.asarray(step.run(toks, labs).asnumpy()[-1]))
    return _finish_record(
        {"model": "llama_tiny_arch", "batch": batch, "seq_len": seq_len,
         "scan_steps": scan_steps, "dtype": "float32"},
        "parallel.TrainStep", steady, steps_per_iter=scan_steps,
        counter_names=("mxnet_sharding_step_dispatches_total",
                       "mxnet_sharding_retraces_total"))


def _lane_multichip():
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd, parallel, sharding

    from mxnet_tpu.gluon.model_zoo.llama import llama_model

    vocab, seq, batch = 64, 16, 16
    mx.random.seed(29)
    np.random.seed(29)
    net = llama_model("llama_tiny", vocab_size=vocab)
    net.initialize(mx.initializer.Normal(0.05))

    def loss_fn(o, l):  # noqa: E741 — labels
        return mx.nd.softmax_cross_entropy(
            o.reshape((-1, o.shape[-1])), l.reshape((-1,))) / l.size

    st = parallel.TrainStep(
        net, loss_fn, mx.optimizer.Adam(learning_rate=1e-3),
        mesh=parallel.DeviceMesh(shape=(2, 2, 2),
                                 axis_names=("dp", "fsdp", "tp")),
        donate=True, partition_rules=sharding.llama_fsdp_rules(),
        data_spec=("dp",))
    r = np.random.RandomState(23)
    toks = r.randint(0, vocab, (batch, seq)).astype("int32")
    labs = np.roll(toks, -1, axis=1).astype("int32")

    def one_step():
        return st(nd.array(toks, dtype="int32"),
                  nd.array(labs, dtype="int32"))

    _begin_capture()
    float(one_step().asscalar())                      # compile + warmup
    steady = _steady_capture(one_step, iters=2)
    float(one_step().asscalar())                      # drain
    return _finish_record(
        {"model": "llama_tiny", "batch": batch, "seq_len": seq,
         "mesh": "dp2xfsdp2xtp2", "rules": "llama_fsdp_rules",
         "donate": True, "dtype": "float32"},
        "parallel.TrainStep", steady,
        counter_names=("mxnet_sharding_step_dispatches_total",
                       "mxnet_sharding_retraces_total"))


def _build_llama_tiny(seed):
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import llama
    mx.random.seed(seed)
    np.random.seed(seed)
    net = llama.llama_model("llama_tiny", vocab_size=101)
    net.initialize(mx.initializer.Normal(0.05))
    net(mx.nd.array(np.zeros((1, 4), np.int32)))      # finish deferred init
    return net


_SERVING_COUNTERS = (
    "mxnet_serving_prefill_positions_total",
    "mxnet_serving_token_positions_total",
    "mxnet_serving_tokens_total",
    "mxnet_serving_decode_steps_total",
    "mxnet_serving_requests_completed_total",
)


def _lane_serving_continuous():
    from mxnet_tpu import serving

    net = _build_llama_tiny(7)
    sysp = [40 + i for i in range(8)]         # 2 shared full blocks
    prompts = [sysp + [70], sysp + [71, 72], [5, 9, 11],
               [7, 8, 9, 10, 3, 4], [12] * 9, [90]]
    eng = serving.ServingEngine(net, eos_id=-1, max_batch=4, block_tokens=4,
                                max_seq=64, prefill_tokens=16,
                                prefix_cache=True)
    _begin_capture()
    eng.generate([[1, 2, 3]], max_new_tokens=2)       # compile + warmup
    steady = _steady_capture(
        lambda: eng.generate(prompts, max_new_tokens=8), iters=1)
    return _finish_record(
        {"model": "llama_tiny", "requests": len(prompts), "max_batch": 4,
         "block_tokens": 4, "max_new_tokens": 8, "prefix_cache": True},
        "serving.llama_decode", steady,
        counter_names=_SERVING_COUNTERS + (
            "mxnet_serving_prefix_hits_total",
            "mxnet_serving_prefix_hit_tokens_total"))


def _lane_serving_spec_decode():
    from mxnet_tpu import serving

    net = _build_llama_tiny(7)
    draft = _build_llama_tiny(23)             # divergent draft, same arch
    prompts = [[5, 9, 11], [7, 8, 9, 10, 3, 4], [40, 41], [12] * 9]
    eng = serving.ServingEngine(net, eos_id=-1, max_batch=4, block_tokens=4,
                                max_seq=64, prefill_tokens=16,
                                draft_model=draft, spec_k=3)
    _begin_capture()
    eng.generate([[1, 2, 3]], max_new_tokens=2)
    steady = _steady_capture(
        lambda: eng.generate(prompts, max_new_tokens=8), iters=1)
    return _finish_record(
        {"model": "llama_tiny", "draft": "llama_tiny", "spec_k": 3,
         "requests": len(prompts), "max_batch": 4, "max_new_tokens": 8},
        "serving.llama_multi", steady,
        counter_names=_SERVING_COUNTERS + (
            "mxnet_serving_draft_steps_total",
            "mxnet_serving_accepted_draft_tokens"))


def _lane_trainer_fused_kvstore():
    """The un-fusing red-path lane: a bert-ish gradient set through the
    fused pushpull.  ``MXNET_KVSTORE_BUCKET_MB=0`` degrades it to the
    per-key loop — the dispatch-per-step explosion the gate must catch
    (tests/test_perfgate.py injects exactly that)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    mx.random.seed(0)
    np.random.seed(0)
    shapes = [(256, 64)]
    for _ in range(2):                    # 2 "layers" of mixed tensors
        shapes += [(64, 64)] * 4 + [(64, 256), (256, 64)] + [(64,)] * 4
    shapes += [(64, 256)]
    kv = mx.kv.create("local")
    keys, grads, outs = [], [], []
    for i, s in enumerate(shapes):
        r = np.random.RandomState(i)
        k = f"w{i}"
        kv.init(k, nd.array(r.randn(*s).astype(np.float32)))
        keys.append(k)
        # 2 replicas per key: the reduce is real math, so both the fused
        # and the degraded per-key path dispatch through armed jits
        grads.append([nd.array(r.randn(*s).astype(np.float32)),
                      nd.array(r.randn(*s).astype(np.float32))])
        outs.append(nd.array(np.zeros(s, np.float32)))

    _begin_capture()
    kv.pushpull_list(keys, grads, outs)               # compile + warmup
    outs[0].asnumpy()
    steady = _steady_capture(
        lambda: kv.pushpull_list(keys, grads, outs), iters=2,
        extra_dispatch_counters=("mxnet_kvstore_push_seconds",
                                 "mxnet_kvstore_pull_seconds",
                                 "mxnet_kvstore_fused_buckets_total"))
    outs[0].asnumpy()                                 # drain
    return _finish_record(
        {"tensors": len(shapes), "bucket_mb":
         config.get_float("MXNET_KVSTORE_BUCKET_MB", 25.0),
         "dtype": "float32"},
        "kvstore.fusion.reduce", steady,
        counter_names=("mxnet_kvstore_fused_buckets_total",
                       "mxnet_kvstore_fused_keys_total",
                       "mxnet_kvstore_fused_pushpulls_total",
                       "mxnet_kvstore_push_bytes_total",
                       "mxnet_kvstore_pull_bytes_total"))


# name -> (runner, virtual device count, description).  The CLI parent
# pins XLA_FLAGS per lane so an inherited device-count override can
# never skew a record.
LANES = {
    "bert_headline": (_lane_bert_headline, 1,
                      "bert_3_128_2 b4 s32 scan2 train step (CI config)"),
    "bert_seq512": (_lane_bert_seq512, 1,
                    "bert_3_128_2 b2 s512 scan2 train step"),
    "llama_longseq": (_lane_llama_longseq, 1,
                      "llama 2L/64u seq-2048 causal-LM train step"),
    "multichip_dp2fsdp2tp2": (_lane_multichip, 8,
                              "llama_tiny dp2xfsdp2xtp2 donated fsdp step"),
    "serving_continuous": (_lane_serving_continuous, 1,
                           "paged-KV continuous batching + prefix cache"),
    "serving_spec_decode": (_lane_serving_spec_decode, 1,
                            "speculative decode, divergent draft, k=3"),
    "trainer_fused_kvstore": (_lane_trainer_fused_kvstore, 1,
                              "fused gradient pushpull (red-path lane)"),
}


def lane_names():
    return list(LANES)


def lane_device_count(name):
    return LANES[name][1]


def run_lane(name):
    """Execute one lane in THIS process (jax required) and return its
    record.  The CLI runs each lane in a fresh child so compile caches
    and registries can never leak across lanes."""
    if name not in LANES:
        raise KeyError(f"unknown perfgate lane {name!r}; "
                       f"have {sorted(LANES)}")
    return LANES[name][0]()
