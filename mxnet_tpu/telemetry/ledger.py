"""Per-op aggregate ledger — the ``aggregate_stats.cc`` analog.

One row per op name: [count, total_s, min_s, max_s], fed by the dispatch
instrumentation (ops.registry) and by profiler scopes/tasks/markers.  The
profiler facade renders this as its table / JSON aggregate formats; it lives
here so telemetry has no import edge back into mx.profiler.

``set_aggregate_stats(False)`` (profiler.set_config parity) turns
accumulation off without touching span tracing or metrics.
"""

from __future__ import annotations

import threading
from collections import defaultdict

__all__ = ["record_op", "snapshot", "clear", "set_aggregate_stats",
           "aggregate_stats"]

_lock = threading.Lock()
_aggregate: dict = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
_enabled = True


def set_aggregate_stats(flag):
    global _enabled
    _enabled = bool(flag)


def aggregate_stats():
    return _enabled


def record_op(name, seconds):
    """One dispatch observation (the ExecuteOprBlock hook analog)."""
    if not _enabled:
        return
    with _lock:
        ent = _aggregate[name]
        ent[0] += 1
        ent[1] += seconds
        ent[2] = min(ent[2], seconds)
        ent[3] = max(ent[3], seconds)


def snapshot(reset=False):
    """{name: (count, total_s, min_s, max_s)}, optionally clearing."""
    with _lock:
        snap = {k: tuple(v) for k, v in _aggregate.items()}
        if reset:
            _aggregate.clear()
    return snap


def clear():
    with _lock:
        _aggregate.clear()
