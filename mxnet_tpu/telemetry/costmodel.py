"""Analytic cost/memory observatory — the hardware-free perf substrate.

Every on-chip perf claim since the axon tunnel died (PROFILE.md r6–r9) is
parked as a measurement recipe; meanwhile XLA will happily *tell* us the
flops, bytes and HBM footprint of every program we compile, with no
hardware attached: JAX's AOT API exposes the compiler's own cost model
(``jitted.lower(...).cost_analysis()`` — flops + bytes accessed) and the
compiled executable's buffer assignment (``.compile().memory_analysis()``
— argument/output/temp/generated-code bytes).  This module turns those
into a first-class observability layer (ISSUE 12 tentpole):

- **compile/cost ledger** (:class:`CostLedger`, module-global ``LEDGER``)
  — every jit boundary the runtime owns (ops.registry dispatch,
  ``parallel.TrainStep``, the fused optimizer/kvstore bucket executables,
  the serving prefill/decode entries) routes through :func:`wrap_jit`.
  When the ledger is **armed** (``MXNET_COSTMODEL=1`` or :func:`arm`),
  each new executable records its measured compile seconds (via the
  ``jax.monitoring`` duration events, attributed by a thread-local site
  tag), its ``cost_analysis`` flops / bytes-accessed, and its
  ``memory_analysis`` argument/output/temp bytes → a per-device peak-HBM
  estimate.  Disarmed, the wrapper costs one module-flag read per call
  (and the per-op dispatch path is not wrapped at all).
- **analytic MFU / roofline** (:func:`roofline`, :func:`lane_summary`) —
  ledger flops + a measured step wall-time give *analytic MFU* (the flops
  XLA counted, not a hand-derived 6N formula), arithmetic intensity, and
  the compute- vs memory-bound roofline verdict against the chip's peak
  flops and HBM bandwidth (``MXNET_PEAK_FLOPS`` / ``MXNET_PEAK_HBM_GBS``
  override the built-in device table).  ``bench.py`` embeds this in every
  BENCH row; ``telemetry.report(cost=True)`` renders the site table.
- **fits-per-shape estimator** (:func:`estimate_memory`) — analytic
  per-device HBM for one fused training step (params + optimizer state +
  grads + batch + activations) under a declarative rule pack on a named
  mesh shape: PROFILE.md r9's hand-derived crossover table, computed.
  Validated against ``memory_analysis`` on the (2,2,2) llama lane
  (``__graft_entry__.dryrun_multichip`` + tests/test_costmodel.py); this
  is the input contract for the ROADMAP-3 auto-sharder.

The AOT analysis costs one extra trace per new executable (cheap) and —
for the memory numbers — one extra XLA compile (``MXNET_COSTMODEL_MEMORY
=0`` skips it); both happen only at executable-build time, so the
steady-state step overhead stays inside the telemetry 2% gate.

Import discipline: jax is imported lazily inside the armed paths only —
``tools/telemetry_report.py`` loads this package standalone without jax.
"""

from __future__ import annotations

import threading
import time
import warnings

from .. import config
from . import metrics as _metrics

__all__ = [
    "LEDGER", "CostLedger", "arm", "disarm", "armed", "wrap_jit",
    "wrap_jit_if_armed", "add_rearm_hook", "peak_flops",
    "peak_hbm_bytes_per_s", "roofline", "lane_summary", "estimate_memory",
    "report_text", "summarize_entries", "site_table_lines",
]

_ARMED = False
_lock = threading.Lock()
_REARM_HOOKS: list = []
_LISTENER_INSTALLED = False

# Compile detection rides jax.monitoring: every trace/lower/compile phase
# fires a duration event, so the listener bumps a global TICK and banks
# the durations.  A wrapper's steady-state armed cost is then ONE int
# compare — it re-probes its executable cache only after the tick moved
# (i.e. something, somewhere, compiled).  Duration attribution is
# best-effort under concurrent compiles from several threads (the drained
# pool is credited to the first wrapper that claims it); single-threaded
# dispatch — the normal case — attributes exactly.
_COMPILE_TICK = 0
_PENDING_COMPILE_S: list = []
_pending_lock = threading.Lock()

# peak table: per-chip bf16 peak flops and HBM bandwidth; the CPU rows are
# nominal figures for a modern server core-complex so roofline verdicts
# stay meaningful on the virtual platform (override with the knobs).
_CPU_PEAK_FLOPS = 5e11        # bench.py's long-standing CPU convention
_CPU_PEAK_BYTES_PER_S = 5e10
_TPU_PEAKS = {                # device_kind substring -> (bf16 flops, B/s)
    "v5p": (459e12, 2765e9),
    "v4": (275e12, 1228e9),
    "v5": (197e12, 819e9),    # v5e / "TPU v5 lite" (the bench chip)
}

_M_EXECUTABLES = _metrics.counter(
    "mxnet_costmodel_executables_total",
    "Executables recorded into the cost ledger (one per (site, input "
    "signature) build while armed).")
_M_ANALYSIS_ERRORS = _metrics.counter(
    "mxnet_costmodel_analysis_errors_total",
    "Ledger AOT analyses that failed (entry records the error string).")
_M_COMPILE_SECONDS = _metrics.histogram(
    "mxnet_costmodel_compile_seconds",
    "Measured trace+lower+compile seconds per recorded executable.",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
             60.0, 120.0, 300.0, 600.0))


def armed():
    """True while the ledger records (knob MXNET_COSTMODEL or arm())."""
    return _ARMED


def add_rearm_hook(fn):
    """Register a callback run on every arm()/disarm() — jit-cache owners
    (ops.registry) use it to drop executables built under the other mode
    so their next build picks the right wrapping."""
    with _lock:
        if fn not in _REARM_HOOKS:
            _REARM_HOOKS.append(fn)


def _run_rearm_hooks():
    with _lock:
        hooks = list(_REARM_HOOKS)
    for fn in hooks:
        try:
            fn()
        except Exception:  # noqa: BLE001 — a cache clear must not sink arming
            pass


def arm():
    """Start recording; returns the previous armed state."""
    global _ARMED
    prev = _ARMED
    _install_listener()
    with _pending_lock:      # stale pool from a prior armed era must not
        _PENDING_COMPILE_S.clear()   # skew the first new attribution
    _ARMED = True
    if not prev:
        _run_rearm_hooks()
    return prev


def disarm():
    global _ARMED
    prev = _ARMED
    _ARMED = False
    if prev:
        _run_rearm_hooks()
    return prev


def _install_listener():
    """Attribute jax's compile-phase duration events (trace / lower /
    backend-compile) to the site currently dispatching on this thread."""
    global _LISTENER_INSTALLED
    with _lock:
        if _LISTENER_INSTALLED:
            return
        _LISTENER_INSTALLED = True
    try:
        import jax.monitoring as jm
        jm.register_event_duration_secs_listener(_on_duration_event)
    except Exception:  # noqa: BLE001 — no jax (offline report tooling)
        pass


def _on_duration_event(name, seconds, **kwargs):  # noqa: ARG001
    global _COMPILE_TICK
    if not _ARMED or "/compile/" not in name:
        return   # disarmed-era compiles must not bank (the listener
        #          stays registered across disarm/arm cycles)
    if getattr(_ANALYSIS_TLS, "active", False):
        return   # the ledger's own AOT compiles must not bank/tick
    with _pending_lock:
        _PENDING_COMPILE_S.append(float(seconds))
        _COMPILE_TICK += 1


_ANALYSIS_TLS = threading.local()


def _drain_compile_seconds():
    with _pending_lock:
        total = sum(_PENDING_COMPILE_S)
        _PENDING_COMPILE_S.clear()
    return total


# -- abstraction: call args -> lowerable avals -------------------------------

def _abstract_leaf(x, keep_sharding):
    import jax
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return x                       # static / scalar python value
    if keep_sharding:
        try:
            sh = x.sharding
            return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sh)
        except Exception:  # noqa: BLE001 — deleted/np arrays, odd leaves
            pass
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _abstract_tree(x, keep_sharding):
    """Args → ShapeDtypeStructs, recursing ONLY through plain containers.
    Namedtuples/dataclass configs pass through untouched — they are the
    static_argnums side of the serving jits and must stay concrete."""
    if type(x) in (tuple, list):
        return type(x)(_abstract_tree(v, keep_sharding) for v in x)
    if type(x) is dict:
        return {k: _abstract_tree(v, keep_sharding) for k, v in x.items()}
    return _abstract_leaf(x, keep_sharding)


def _cost_dict(lowered):
    c = lowered.cost_analysis()
    if isinstance(c, (list, tuple)):    # some backends: one dict per comp
        merged: dict = {}
        for d in c:
            for k, v in (d or {}).items():
                merged[k] = merged.get(k, 0.0) + v
        c = merged
    return c or {}


# -- the ledger --------------------------------------------------------------

class CostLedger:
    """Thread-safe per-executable cost/memory records + per-site tallies.

    Call counting stays OFF the armed hot path: each wrapper bumps its
    own lock-free ``_calls`` int (a dropped increment under a thread race
    costs one count, never a crash) and the ledger sums them on read."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: list = []
        self._wrappers: list = []       # weakrefs to _InstrumentedJit's

    # -- feeding (wrappers call these while armed) --------------------------

    def _register(self, wrapper):
        import weakref
        with self._lock:
            self._wrappers.append(weakref.ref(wrapper))
            if len(self._wrappers) % 512 == 0:   # bound growth
                self._wrappers[:] = [r for r in self._wrappers
                                     if r() is not None]

    def _call_counts(self):
        """site -> armed dispatches through currently-live wrappers (a
        rebuilt executable starts a fresh count, like its compile cache)."""
        with self._lock:
            refs = list(self._wrappers)
        out: dict = {}
        for r in refs:
            w = r()
            if w is not None and w._calls:
                out[w.site] = out.get(w.site, 0) + w._calls
        return out

    def analyze(self, site, jf, args, kwargs, compile_s=0.0):
        """AOT-analyze the executable ``jf`` just built for ``args`` and
        append the record.  Never raises: an analysis failure records an
        ``error`` entry (counted) and execution continues untouched."""
        t0 = time.perf_counter()
        entry = {"site": site, "compile_s": float(compile_s),
                 "time": time.time()}
        _ANALYSIS_TLS.active = True
        try:
            with warnings.catch_warnings():
                # lowering with donated-but-unused avals warns; the
                # analysis pass must stay silent
                warnings.simplefilter("ignore")
                entry.update(self._analyze_once(jf, args, kwargs))
        except Exception as e:  # noqa: BLE001 — ledger must never kill a step
            entry["error"] = f"{type(e).__name__}: {e}"[:300]
            _M_ANALYSIS_ERRORS.inc()
        finally:
            _ANALYSIS_TLS.active = False
        entry["analysis_s"] = round(time.perf_counter() - t0, 4)
        with self._lock:
            entry["index"] = sum(1 for e in self._entries
                                 if e["site"] == site)
            self._entries.append(entry)
        _M_EXECUTABLES.inc()
        if compile_s:
            _M_COMPILE_SECONDS.observe(compile_s)
        return entry

    def _analyze_once(self, jf, args, kwargs):
        try:
            a = _abstract_tree(tuple(args), True)
            k = {n: _abstract_tree(v, True) for n, v in kwargs.items()}
            lowered = jf.lower(*a, **k)
        except Exception:  # noqa: BLE001 — sharding-annotated avals can
            # clash with explicit in_shardings; retry shardings-free
            a = _abstract_tree(tuple(args), False)
            k = {n: _abstract_tree(v, False) for n, v in kwargs.items()}
            lowered = jf.lower(*a, **k)
        cost = _cost_dict(lowered)
        out = {
            "flops": float(cost.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0) or 0.0),
        }
        if config.get_int("MXNET_COSTMODEL_MEMORY", 1):
            ma = lowered.compile().memory_analysis()
            arg_b = int(ma.argument_size_in_bytes)
            out_b = int(ma.output_size_in_bytes)
            tmp_b = int(ma.temp_size_in_bytes)
            code_b = int(ma.generated_code_size_in_bytes)
            alias_b = int(getattr(ma, "alias_size_in_bytes", 0) or 0)
            out.update(
                arg_bytes=arg_b, out_bytes=out_b, temp_bytes=tmp_b,
                code_bytes=code_b, alias_bytes=alias_b,
                # donated outputs alias their argument buffers — peak is
                # what must coexist per device, not the naive sum
                peak_bytes=arg_b + tmp_b + code_b + max(0, out_b - alias_b))
        return out

    # -- reading ------------------------------------------------------------

    def entries(self, site=None):
        with self._lock:
            ents = list(self._entries)
        if site is None:
            return ents
        return [e for e in ents if e["site"] == site]

    def calls(self, site):
        return self._call_counts().get(site, 0)

    def site_summary(self):
        """{site: {executables, calls, compile_s, flops, bytes_accessed,
        peak_bytes, errors}} — flops/bytes/peak from each site's largest
        recorded executable (the steady-state program; warmup shapes and
        probe dispatches are smaller)."""
        with self._lock:
            ents = list(self._entries)
        return summarize_entries(ents, self._call_counts())

    def snapshot(self):
        """JSON-serializable ledger state — rides the telemetry snapshot
        (aggregate.snapshot) and the /ledger.json endpoint."""
        with self._lock:
            ents = [dict(e) for e in self._entries]
        return {"entries": ents, "calls": self._call_counts()}

    def clear(self):
        with self._lock:
            self._entries.clear()
            live = []
            for r in self._wrappers:
                w = r()
                if w is not None:
                    w._calls = 0
                    live.append(r)
            self._wrappers[:] = live


def summarize_entries(entries, calls=None):
    """Per-site roll-up of raw ledger entry dicts — shared by the live
    :meth:`CostLedger.site_summary` and the offline report CLI, which
    reads the ``costmodel`` block of exported telemetry shards."""
    calls = calls or {}
    out: dict = {}
    for e in entries:
        s = out.setdefault(e["site"], {
            "executables": 0, "calls": calls.get(e["site"], 0),
            "compile_s": 0.0, "flops": 0.0, "bytes_accessed": 0.0,
            "peak_bytes": 0, "errors": 0})
        s["executables"] += 1
        s["compile_s"] += e.get("compile_s", 0.0)
        if e.get("error"):
            s["errors"] += 1
            continue
        if (e.get("flops") or 0.0) >= s["flops"]:
            s["flops"] = e.get("flops") or 0.0
            s["bytes_accessed"] = e.get("bytes_accessed") or 0.0
        s["peak_bytes"] = max(s["peak_bytes"], e.get("peak_bytes", 0) or 0)
    return out


LEDGER = CostLedger()


# -- the jit-boundary wrapper ------------------------------------------------

class _InstrumentedJit:
    """Transparent wrapper over one jitted callable: armed, it tags the
    dispatch with its site (compile-duration attribution) and AOT-analyzes
    every NEW executable the underlying cache builds; disarmed, one flag
    read.  The armed steady-state cost is lock-free: a local call-count
    bump, one thread-local set/restore pair, and one C++ cache-size probe
    — analysis work happens only when the cache GREW (a compile, which
    already cost seconds)."""

    __slots__ = ("_jf", "site", "_nexec", "_calls", "_tick", "__weakref__")

    def __init__(self, jf, site):
        self._jf = jf
        self.site = site
        self._nexec = 0
        self._calls = 0
        self._tick = -1     # forces a first-armed-call cache probe, so
        #                     arming AFTER an executable was built still
        #                     records it lazily on its next dispatch
        LEDGER._register(self)

    def __getattr__(self, name):        # .lower / ._cache_size passthrough
        return getattr(self._jf, name)

    def __call__(self, *args, **kwargs):
        if not _ARMED:
            return self._jf(*args, **kwargs)
        out = self._jf(*args, **kwargs)
        self._calls += 1
        if self._tick != _COMPILE_TICK:     # something compiled: was it us?
            self._probe(args, kwargs)
        return out

    def _cache_size(self):
        try:
            return self._jf._cache_size()
        except Exception:  # noqa: BLE001 — private API; fall back below
            return None

    def _probe(self, args, kwargs):
        self._tick = _COMPILE_TICK
        n = self._cache_size()
        if n is None:
            # no cache introspection (the private pjit API moved under a
            # jax upgrade): analyze this wrapper at most ONCE — assuming
            # every foreign compile was ours would re-run the AOT
            # analysis (an extra XLA compile each) on every tick move
            if self._nexec:
                return
            n = 1
        if n != self._nexec:
            self._nexec = n
            # drain ONLY when our cache grew — another site's compile
            # leaves the pool for the wrapper that actually owns it
            LEDGER.analyze(self.site, self._jf, args, kwargs,
                           compile_s=_drain_compile_seconds())


def wrap_jit(jf, site):
    """Instrument a jitted callable under a site label.  Use at every
    boundary whose dispatch rate is per-step or slower (TrainStep, fused
    optimizer/kvstore buckets, serving entries): the disarmed cost is one
    flag read, and arming at runtime instruments executables lazily (the
    next dispatch sees the cache already populated and analyzes it)."""
    return _InstrumentedJit(jf, site)


def wrap_jit_if_armed(jf, site):
    """Instrument only when already armed — for the per-op dispatch path,
    which must stay wrapper-free when the ledger is off.  Owners register
    an :func:`add_rearm_hook` cache clear so a runtime arm() rebuilds
    their callables through this with the wrapper on."""
    if _ARMED:
        return _InstrumentedJit(jf, site)
    return jf


# -- analytic MFU + roofline -------------------------------------------------

def peak_flops(dtype="bfloat16"):
    """Per-chip peak flops for MFU accounting.  MXNET_PEAK_FLOPS wins;
    else the device table (bf16 peaks; /4 for float32), CPU nominal."""
    v = config.get_float("MXNET_PEAK_FLOPS", 0.0)
    if v > 0:
        return v
    kind, is_cpu = _device_kind()
    if is_cpu:
        return _CPU_PEAK_FLOPS
    for sub, (bf16, _bw) in _TPU_PEAKS.items():
        if sub in kind:
            break
    else:
        bf16 = _TPU_PEAKS["v5"][0]
    return bf16 if str(dtype) in ("bfloat16", "bf16") else bf16 / 4


def peak_hbm_bytes_per_s():
    """Per-chip HBM bandwidth (B/s) for the roofline ridge.
    MXNET_PEAK_HBM_GBS (in GB/s) wins; else the device table."""
    v = config.get_float("MXNET_PEAK_HBM_GBS", 0.0)
    if v > 0:
        return v * 1e9
    kind, is_cpu = _device_kind()
    if is_cpu:
        return _CPU_PEAK_BYTES_PER_S
    for sub, (_pf, bw) in _TPU_PEAKS.items():
        if sub in kind:
            return bw
    return _TPU_PEAKS["v5"][1]


def _device_kind():
    try:
        import jax
        d = jax.devices()[0]
    except Exception:  # noqa: BLE001 — no jax/backend: treat as CPU
        return "", True
    return str(getattr(d, "device_kind", "")).lower(), d.platform == "cpu"


def roofline(flops, bytes_accessed, seconds=None, dtype="bfloat16"):
    """The roofline read on one program: arithmetic intensity vs the
    machine ridge, the attainable-MFU bound it implies, and (given a
    measured wall time) the analytic MFU actually achieved."""
    pf = peak_flops(dtype)
    pb = peak_hbm_bytes_per_s()
    ai = float(flops) / max(float(bytes_accessed), 1.0)
    ridge = pf / pb
    out = {
        "flops": float(flops),
        "bytes_accessed": float(bytes_accessed),
        "arithmetic_intensity": round(ai, 3),
        "ridge_flops_per_byte": round(ridge, 3),
        "verdict": "compute-bound" if ai >= ridge else "memory-bound",
        # the ceiling the roofline itself allows at this intensity: below
        # the ridge, HBM bandwidth (not the MXU) bounds achievable MFU
        "roofline_mfu_bound": round(min(1.0, ai / ridge), 4),
        "peak_flops": pf,
        "peak_hbm_bytes_per_s": pb,
    }
    if seconds:
        out["analytic_mfu"] = round(float(flops) / (float(seconds) * pf), 4)
        out["flops_per_s"] = float(flops) / float(seconds)
    return out


def lane_summary(site="parallel.TrainStep", step_seconds=None,
                 dtype="bfloat16"):
    """The BENCH-row cost block for one lane: the site's largest recorded
    executable (its steady-state program) rooflined against the chip
    peaks, with the per-device peak-HBM estimate and compile seconds
    alongside.  The program's cost IS the per-step cost even for
    lax.scan-fused lanes — XLA's HLO cost analysis counts a while/scan
    body ONCE regardless of trip count (verified: identical flops at
    scan_steps 2 and 4), so ``step_seconds`` should be the measured
    per-STEP wall time, not per-dispatch."""
    ents = [e for e in LEDGER.entries(site) if not e.get("error")]
    if not ents:
        return {"error": f"no cost-ledger entries for site {site!r} "
                         "(costmodel not armed?)"}
    e = max(ents, key=lambda x: x.get("flops") or 0.0)
    flops = e.get("flops") or 0.0
    byts = e.get("bytes_accessed") or 0.0
    out = roofline(flops, byts, seconds=step_seconds, dtype=dtype)
    out["peak_hbm_bytes"] = e.get("peak_bytes", 0)
    out["compile_s"] = round(sum(x.get("compile_s", 0.0) for x in ents), 3)
    out["executables"] = len(ents)
    return out


def site_table_lines(summary):
    """Formatted per-site table rows from a :func:`summarize_entries`
    dict — the ONE renderer behind ``report_text`` (live) and
    ``tools/telemetry_report.py --cost`` (offline shards)."""
    lines = [f"  {'site':<28} {'exec':>5} {'calls':>7} "
             f"{'compile_s':>10} {'gflops':>10} {'AI':>7} "
             f"{'peak_hbm_mb':>12} {'verdict':<14}"]
    for site in sorted(summary):
        s = summary[site]
        rl = roofline(s["flops"], s["bytes_accessed"])
        lines.append(
            f"  {site:<28} {s['executables']:>5} {s['calls']:>7} "
            f"{s['compile_s']:>10.3f} {s['flops'] / 1e9:>10.3f} "
            f"{rl['arithmetic_intensity']:>7.1f} "
            f"{s['peak_bytes'] / 1e6:>12.2f} {rl['verdict']:<14}")
        if s["errors"]:
            lines.append(f"    ({s['errors']} analysis error(s) — see "
                         "LEDGER.entries())")
    return lines


def report_text():
    """Human-readable per-site ledger table (telemetry.report(cost=True))."""
    summ = LEDGER.site_summary()
    lines = [f"cost ledger ({len(summ)} site(s), "
             f"{sum(s['executables'] for s in summ.values())} "
             f"executable(s)):"]
    if not summ:
        lines.append("  (empty — arm with MXNET_COSTMODEL=1 or "
                     "telemetry.costmodel.arm())")
        return "\n".join(lines)
    lines.extend(site_table_lines(summ))
    return "\n".join(lines)


# -- fits-per-shape: analytic per-device HBM ---------------------------------

def _mesh_axis_sizes(mesh_shape):
    """{'dp': 2, 'tp': 2, ...} from a dict, a DeviceMesh, or a
    (shape, axis_names) pair."""
    if hasattr(mesh_shape, "axis_names"):      # DeviceMesh / jax Mesh
        names = tuple(mesh_shape.axis_names)
        try:
            sizes = tuple(mesh_shape.shape[n] for n in names)  # jax Mesh
        except TypeError:
            sizes = tuple(mesh_shape.shape)
        return dict(zip(names, sizes))
    if isinstance(mesh_shape, dict):
        return {str(k): int(v) for k, v in mesh_shape.items()}
    shape, names = mesh_shape
    return dict(zip(names, (int(s) for s in shape)))


def _sharded_numel(shape, spec, axes):
    """Element count of one param's per-device shard under ``spec`` —
    resolve_spec's exact degradation semantics (missing axes drop out,
    indivisible dims stay whole)."""
    n = 1
    spec = tuple(spec or ())
    for d, dim in enumerate(shape):
        div = 1
        if d < len(spec):
            entry = spec[d]
            entry = entry if isinstance(entry, (tuple, list)) \
                else (entry,) if entry is not None else ()
            for a in entry:
                div *= axes.get(a, 1)
        n *= dim // div if (div > 1 and dim % div == 0) else dim
    return n


def _param_table(model_cfg):
    """{name: (shape, itemsize)} from a Block, ParameterDict, or dict of
    shapes/arrays."""
    import numpy as _np
    if hasattr(model_cfg, "collect_params"):
        model_cfg = model_cfg.collect_params()
    out = {}
    for name, leaf in dict(model_cfg.items()).items():
        shape = tuple(leaf) if isinstance(leaf, (tuple, list)) \
            else tuple(leaf.shape)
        dt = getattr(leaf, "dtype", None)
        out[name] = (shape, _np.dtype(dt).itemsize if dt is not None else 4)
    return out


_EMBED_PAT = ("tok_", "word_", "embed", "position_")


def _drop_axes(spec, drop):
    """``spec`` with every axis in ``drop`` removed (per-dim entries keep
    their remaining axes)."""
    out = []
    for entry in tuple(spec or ()):
        entry = entry if isinstance(entry, (tuple, list)) \
            else (entry,) if entry is not None else ()
        kept = tuple(a for a in entry if a not in drop)
        out.append(kept if len(kept) > 1 else (kept[0] if kept else None))
    return tuple(out)


def estimate_memory(model_cfg, mesh_shape, rule_pack, batch, seq=None,
                    optimizer="adam", multi_precision=False,
                    data_axes=("dp", "sp"), vocab=None,
                    n_micro=1, remat=False, fsdp_axes=("fsdp",)):
    """Analytic per-device HBM (bytes) for ONE fused training step.

    Parameters
    ----------
    model_cfg : a gluon Block (post-init), ParameterDict, or
        ``{name: shape|array}`` dict — the named param tree the rule pack
        matches against.
    mesh_shape : ``{'dp': 2, 'tp': 2, 'sp': 2}``, a DeviceMesh, or a
        ``(shape, axis_names)`` pair.
    rule_pack : pack name (``'llama'``/``'llama_fsdp'``/``'bert'``/...),
        an ordered ``(regex, spec)`` rule list, or None (fully
        replicated).
    batch : GLOBAL batch size (samples).
    seq : tokens per sample (token models; None => 1, feature models).
    optimizer : 'adam' (m+v state) or 'sgd' (momentum assumed on).
    multi_precision : half-precision weights keep fp32 masters.
    data_axes : mesh axes the token batch shards over (data_spec) —
        include the fsdp axis for ZeRO-3 layouts (the batch rides it).
    vocab : LM-head width for the logits term; inferred from the widest
        embedding-named param when None.
    n_micro : gradient-accumulation microbatches per step (TrainStep
        ``n_micro``): live activations/logits divide by it, but a full
        gradient ACCUMULATOR joins the working set (and under fsdp the
        per-microbatch gradients live gathered inside the scan before
        their reduce-scatter — both measured on the llama lane).
    remat : TrainStep ``remat`` — saved activations halve (checkpointed
        segment stores inputs; backward recomputes with roughly half the
        residual set live).  XLA:CPU's compiled peak barely moves under
        whole-net remat (its scheduler already overlaps fwd/bwd), so
        remat'd estimates are NOT cross-checked against memory_analysis;
        the planner treats remat as the last lever (PROFILE.md r11 has
        the on-chip re-measurement recipe).
    fsdp_axes : axes with gather-on-use semantics (params sharded along
        them are all-gathered right before each matmul).

    Returns a breakdown dict whose ``total_bytes`` is the estimated
    steady-state peak for a donated step: live arguments (params +
    optimizer state + batch) plus the backward working set (gradients +
    saved activations + the fp32 logits head + the fsdp gather
    working set).  Validated against ``memory_analysis`` on the dryrun
    llama lanes: 2.6% off on (2,2,2) dp×tp×sp, ~1% on dp×fsdp
    (gather term = half the full-along-fsdp weight bytes, measured),
    ~15% conservative on dp-only — the input contract for the
    auto-sharder (ROADMAP 3).
    """
    axes = _mesh_axis_sizes(mesh_shape)
    table = _param_table(model_cfg)
    if rule_pack is None:
        specs = {name: () for name in table}
    else:
        from .. import sharding as _sh
        rules = _sh.rule_pack(rule_pack) if isinstance(rule_pack, str) \
            else rule_pack
        specs = _sh.match_partition_rules(
            rules, {n: shape for n, (shape, _i) in table.items()})

    if optimizer == "adam":
        n_state = 2
    elif optimizer in ("sgd", "sgd_mom"):
        n_state = 1
    else:
        raise ValueError(f"estimate_memory: unknown optimizer "
                         f"{optimizer!r} (adam|sgd)")
    n_micro = max(1, int(n_micro))

    tokens = int(batch) * int(seq or 1)
    data_div = 1
    for a in data_axes:
        data_div *= axes.get(a, 1)
    tokens_dev = max(1, tokens // data_div)
    # only one microbatch's activations are live at a time
    tokens_act = max(1, tokens_dev // n_micro)

    params_b = state_b = 0
    act_elems = 0.0
    gathered_b = 0          # full-along-fsdp bytes of gather-on-use params
    inferred_vocab = 0
    seen_inputs = set()
    fsdp_drop = frozenset(fsdp_axes)
    for name, (shape, itemsize) in table.items():
        spec = specs.get(name, ())
        numel = _sharded_numel(shape, spec, axes)
        params_b += numel * itemsize
        state_b += numel * itemsize * n_state
        if multi_precision and itemsize < 4:
            state_b += numel * 4
        nofsdp_spec = _drop_axes(spec, fsdp_drop)
        gathered = _sharded_numel(shape, nofsdp_spec, axes)
        if gathered != numel:
            # actually fsdp-sharded (divisible, axis present): the
            # all-gather before use materializes the full-along-fsdp
            # weight (still divided by any tp axes it carries)
            gathered_b += gathered * itemsize
        is_embed = any(p in name for p in _EMBED_PAT)
        if is_embed and len(shape) == 2:
            inferred_vocab = max(inferred_vocab, shape[0])
        if len(shape) == 2 and not is_embed:
            # every matmul's backward saves its input activation
            # (tokens × in_features, sharded when the weight is
            # row-parallel) and hands a same-shaped output cotangent
            # through (tokens × out_features, sharded when
            # column-parallel): count the saved input plus the layer
            # output that the residual stream keeps live.  Matmuls in
            # one layer reading the SAME activation (q/k/v, gate/up)
            # save it ONCE — dedup by (layer prefix, sharded width).
            # Activation widths use the NON-fsdp sharding: the matmul
            # runs on the gathered weight, so activations shard only
            # over tp-style axes.
            out_f = _sharded_numel((shape[0],), nofsdp_spec[:1], axes)
            in_f = _sharded_numel((shape[1],), nofsdp_spec[1:2], axes) \
                if len(nofsdp_spec) > 1 else shape[1]
            layer_key = name.rsplit("_", 2)[0]
            if (layer_key, in_f) not in seen_inputs:
                seen_inputs.add((layer_key, in_f))
                act_elems += tokens_act * in_f
            act_elems += tokens_act * out_f

    # fp32 logits head: softmax_cross_entropy upcasts and saves both the
    # logits and their softmax for backward
    v = int(vocab) if vocab else inferred_vocab
    logits_b = 2 * tokens_act * v * 4 if v else 0
    # gradients live as temps through backward + the fused update; a
    # microbatched step additionally carries the accumulator, and under
    # fsdp the in-scan per-microbatch gradients are FULL along fsdp
    # until their reduce-scatter (measured: llama dp×fsdp micro lane)
    grads_b = params_b
    if n_micro > 1:
        grads_b += gathered_b if gathered_b else params_b
    acts_b = int(act_elems) * 4     # residuals saved in compute precision
    if remat:
        acts_b //= 2
    # gather-on-use working set: roughly half the gathered weight bytes
    # live at the peak while the scheduler can overlap gathers with
    # frees (measured 195.4KB vs 197.6KB predicted on the llama
    # dp2×fsdp4 lane) — but once the live ACTIVATION set outgrows that
    # half, XLA holds the full gathered set (measured crossover on the
    # batch-32 dp4×fsdp2 lane: half-model 14% under, full-model 3%
    # over).  Inside a microbatch scan gathers can't overlap frees
    # across the scan boundary at all, so the full set always counts
    # there (fsdp micro2 lane: within 1.5% with this, 17% under
    # without).
    if n_micro > 1:
        gather_b = gathered_b
    else:
        gather_b = min(gathered_b, max(gathered_b // 2, acts_b))
    batch_b = 2 * tokens_dev * 4    # data + label, int32 tokens
    total = (params_b + state_b + grads_b + batch_b + acts_b + logits_b
             + gather_b)
    return {
        "params_bytes": int(params_b),
        "opt_state_bytes": int(state_b),
        "grads_bytes": int(grads_b),
        "batch_bytes": int(batch_b),
        "activation_bytes": int(acts_b),
        "logits_bytes": int(logits_b),
        "fsdp_gather_bytes": int(gather_b),
        # the UN-clamped full-along-fsdp weight bytes: what one step's
        # all-gathers actually move per microbatch (the residency-
        # clamped fsdp_gather_bytes above is a PEAK-MEMORY quantity and
        # must not be used for communication accounting)
        "fsdp_gathered_bytes": int(gathered_b),
        "total_bytes": int(total),
        "tokens_per_device": tokens_dev,
        "n_micro": n_micro,
        "remat": bool(remat),
        "mesh": dict(axes),
    }


# -- env arming (telemetry.__init__ calls this at import) --------------------

def arm_from_env():
    if config.get_int("MXNET_COSTMODEL", 0):
        arm()
