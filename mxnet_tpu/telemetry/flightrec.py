"""Crash flight recorder — the always-on black box (ISSUE 10 tentpole).

The chaos suite deliberately kills workers mid-collective and preempts
them mid-checkpoint; production jobs die the same ways without a debugger
attached.  This module guarantees every such death leaves a **readable
postmortem per rank**: a bounded JSON dump containing

- the last ``MXNET_FLIGHTREC_SPANS`` trace events (whatever the tracer
  holds — full timeline when telemetry is on, empty when off),
- the complete metric registry state (retrace counters, deadline/fault
  counters, kvstore bytes — these count on several paths even with the
  span tracer off),
- the breadcrumb ring (:func:`note` — tiny always-on markers from
  non-hot chokepoints, independent of the telemetry flag),
- armed chaos sites + faults fired, the step-clock summary, the resolved
  env-knob values, and the exception/traceback when there is one.

Dump triggers (installed once at import when ``MXNET_FLIGHTREC=1``, the
default):

- **unhandled exceptions** — a chained ``sys.excepthook``;
- **deadline expiry** — ``resilience.Deadline`` dumps right before
  raising ``KVStoreTimeoutError`` (a dead peer's survivors all leave
  postmortems, which is how an n=4 chaos death becomes diagnosable);
- **chaos 'exit' faults** — ``resilience.chaos`` dumps before
  ``os._exit`` (the one death no hook survives);
- **SIGTERM** — dump, then chain to the previous handler (or re-deliver
  the default), composing with the checkpoint preemption hook;
- **SIGUSR2** — dump on demand and keep running (live inspection of a
  stuck job: ``kill -USR2 <pid>``).

Dumps are atomic (write-then-rename, the checkpoint manifest discipline),
bounded in count (``MXNET_FLIGHTREC_MAX_DUMPS`` per process) and land in
``MXNET_FLIGHTREC_DIR`` (default: ``MXNET_TELEMETRY_DIR``, else
``~/.cache/mxnet_tpu/flightrec`` — never the working tree).  When a telemetry collection dir is configured, a dump
also exports this rank's telemetry snapshot — so a crashed rank still
contributes to the merged trace.  :func:`dump` never raises and nothing
here imports jax.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque

from .. import config
from . import aggregate, ledger, metrics, stepclock, tracer

__all__ = ["note", "dump", "install", "enabled", "dump_dir"]

_lock = threading.Lock()
_breadcrumbs: deque = deque(maxlen=64)
_installed = False
_prev_excepthook = None
_prev_sigterm = None
_prev_sigusr2 = None
_dumps = 0


def enabled():
    return bool(config.get_int("MXNET_FLIGHTREC", 1))


def dump_dir():
    d = config.get("MXNET_FLIGHTREC_DIR") or config.get("MXNET_TELEMETRY_DIR")
    if d:
        return d
    # default OUTSIDE the working tree (satellite: bench/example runs
    # from a source checkout were littering ./flightrec into the repo);
    # spawned workers inherit MXNET_FLIGHTREC_DIR, so one process-wide
    # redirect covers a whole job
    return os.path.join(os.path.expanduser("~"), ".cache", "mxnet_tpu",
                        "flightrec")


def note(event, **attrs):
    """Always-on breadcrumb (bounded ring, independent of the telemetry
    flag) — call from non-hot chokepoints so the black box carries a
    trail even in telemetry-off runs."""
    crumb = {"t": time.time(), "event": str(event)}
    if attrs:
        crumb.update(attrs)
    with _lock:
        _breadcrumbs.append(crumb)


def _record(reason, exc=None):
    n_spans = max(1, config.get_int("MXNET_FLIGHTREC_SPANS", 256))
    tr = tracer.get_tracer()
    events = tr.events()
    with _lock:
        crumbs = list(_breadcrumbs)
    rec = {
        "reason": reason,
        "time": time.time(),
        "time_iso": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "rank": aggregate.rank(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "telemetry_enabled": tracer.enabled(),
        "spans": events[-n_spans:],
        "spans_dropped": tr.dropped + max(0, len(events) - n_spans),
        "thread_names": {str(k): v for k, v in tr.thread_names().items()},
        "breadcrumbs": crumbs,
        "metrics": metrics.REGISTRY.export_state(),
        "stepclock": stepclock.STEP_CLOCK.summary(),
        "ledger_top": sorted(
            ((k, list(v)) for k, v in ledger.snapshot().items()),
            key=lambda kv: -kv[1][1])[:20],
        "config": {name: cur for name, cur, _default, _doc
                   in config.describe() if cur is not None},
    }
    try:
        # lazy: resilience imports telemetry, never the other way around
        from ..resilience import chaos as _chaos
        rec["chaos"] = {"armed_sites": _chaos.sites(),
                        "faults_fired": _chaos.fault_count()}
    except Exception:  # noqa: BLE001 — resilience may not be importable yet
        pass
    if exc is not None:
        rec["exception"] = {
            "type": type(exc).__name__,
            "message": str(exc)[:2000],
            "traceback": traceback.format_exception(
                type(exc), exc, getattr(exc, "__traceback__", None))[-50:],
        }
    return rec


def _slug(reason):
    return "".join(c if (c.isalnum() or c in ".-") else "-"
                   for c in str(reason))[:80] or "dump"


def dump(reason, exc=None, directory=None):
    """Write one postmortem atomically; returns its path.  NEVER raises
    and never dumps more than MXNET_FLIGHTREC_MAX_DUMPS times per process
    (a retry loop hitting deadlines must not flood the disk).  Returns
    None when disabled, capped, or the write failed."""
    global _dumps
    if not enabled():
        return None
    try:
        with _lock:
            if _dumps >= config.get_int("MXNET_FLIGHTREC_MAX_DUMPS", 16):
                return None
            _dumps += 1
            seq = _dumps
        d = directory or dump_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"flightrec-rank{aggregate.rank():05d}-pid{os.getpid()}"
               f"-{seq:02d}-{_slug(reason)}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(_record(reason, exc), f, indent=1, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        try:
            # the black box doubles as this rank's telemetry export: a
            # crashed rank still contributes to the merged trace
            aggregate.export_snapshot()
        except Exception:  # noqa: BLE001
            pass
        return path
    except Exception:  # noqa: BLE001 — a failing dump must not mask the crash
        return None


def _reset_dump_cap_for_test():
    """Testing hook: clear the per-process dump budget."""
    global _dumps
    with _lock:
        _dumps = 0


# -- triggers ---------------------------------------------------------------

def _excepthook(etype, value, tb):
    dump(f"exception.{etype.__name__}", exc=value)
    prev = _prev_excepthook or sys.__excepthook__
    prev(etype, value, tb)


def _dump_from_handler(reason, join_s):
    """Dump from INSIDE a signal handler without deadlocking: the handler
    runs on the interrupted main thread, which may hold any of the
    non-reentrant locks dump() needs (a metric's lock mid-observe, the
    breadcrumb lock).  A daemon thread takes them safely; the bounded
    join keeps SIGTERM death prompt — if the thread is blocked on a lock
    the interrupted frame holds, the join times out, the handler returns
    (or re-delivers death), and the thread finishes the dump once the
    frame resumes and releases the lock (when the process lives on)."""
    t = threading.Thread(target=dump, args=(reason,), daemon=True,
                         name="mx-flightrec-dump")
    t.start()
    if join_s:
        t.join(join_s)


def _on_sigterm(signum, frame):
    _dump_from_handler("sigterm", join_s=5.0)
    prev = _prev_sigterm
    if callable(prev):
        prev(signum, frame)
    elif prev is None or prev == signal.SIG_DFL:
        # re-deliver the default disposition (die) instead of swallowing.
        # prev None means the prior handler lived at the C level
        # (embedded interpreter / launcher preload) — unknowable from
        # here, and for SIGTERM "terminate" is the only safe reading
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)
    # SIG_IGN: stay ignored


def _on_sigusr2(signum, frame):  # noqa: ARG001 — signal handler shape
    _dump_from_handler("sigusr2", join_s=0)   # live process: no need to wait


def install():
    """Arm the triggers once: excepthook always; SIGTERM/SIGUSR2 only
    from the main thread (signal.signal's contract).  Idempotent;
    telemetry.__init__ calls this at import when MXNET_FLIGHTREC=1."""
    global _installed, _prev_excepthook, _prev_sigterm, _prev_sigusr2
    with _lock:
        if _installed:
            return
        _installed = True
        _prev_excepthook = sys.excepthook
        sys.excepthook = _excepthook
        if threading.current_thread() is not threading.main_thread():
            return
        try:
            _prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):   # no signal support here
            pass
        try:
            if hasattr(signal, "SIGUSR2"):
                _prev_sigusr2 = signal.signal(signal.SIGUSR2, _on_sigusr2)
        except (ValueError, OSError):
            pass
