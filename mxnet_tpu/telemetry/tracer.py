"""Structured span tracer — the host-side timeline half of mx.telemetry.

Rebuild of the reference profiler's event recorder (src/profiler/profiler.cc
``ProfileStat`` ring + ``DumpProfile``): every ``span()`` records begin/end
host timestamps into a bounded ring buffer; ``chrome_trace()`` renders the
buffer as genuine Chrome-trace JSON (``traceEvents`` with ``ph:"X"`` complete
events) that chrome://tracing / Perfetto load directly.

Overhead discipline: recording is gated on the module-level ``_ENABLED``
flag.  When off, ``span()`` returns a shared stateless no-op context manager
and hot paths (ops.registry dispatch) skip instrumentation after a single
flag check.  Nothing here imports jax — the module is safe to import on any
hot path.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from .. import config

__all__ = ["Span", "Tracer", "span", "instant", "async_event", "enable",
           "disable", "enabled", "get_tracer", "clear", "chrome_trace"]

# Single flag gating ALL recording.  Rebound by enable()/disable(); hot
# paths read it as a module attribute (one load, no call).
_ENABLED = False


class _NullSpan:
    """Shared stateless no-op returned by span() when telemetry is off."""

    __slots__ = ()
    duration_s = 0.0
    attrs: dict = {}  # read-only by convention; set() never writes it

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):  # noqa: ARG002
        return self


NULL_SPAN = _NullSpan()


class Span:
    """One timed region.  Context-manager; records on exit."""

    __slots__ = ("_tracer", "name", "category", "attrs", "_t0", "_t1")

    def __init__(self, tracer, name, category, attrs):
        self._tracer = tracer
        self.name = name
        self.category = category
        self.attrs = attrs
        self._t0 = None
        self._t1 = None

    def set(self, **attrs):
        """Attach attributes mid-span (rendered under Chrome-trace args)."""
        self.attrs.update(attrs)
        return self

    @property
    def duration_s(self):
        if self._t0 is None or self._t1 is None:
            return 0.0
        return (self._t1 - self._t0) / 1e9

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self._t1 = time.perf_counter_ns()
        self._tracer.add_event(self.name, self.category, self._t0, self._t1,
                               self.attrs)
        return False


class Tracer:
    """Thread-safe bounded ring buffer of trace events.

    Events are stored as ready-to-serialize Chrome-trace dicts (``ph:"X"``
    complete events, timestamps in microseconds relative to tracer start)
    so export is a snapshot, not a transform.
    """

    def __init__(self, capacity=None):
        if capacity is None:
            capacity = config.get_int("MXNET_TELEMETRY_BUFFER", 65536)
        self._events = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._t0_ns = time.perf_counter_ns()
        self._dropped = 0
        self._tid_names: dict = {}
        self._process_label = "mxnet_tpu"

    @property
    def capacity(self):
        return self._events.maxlen

    @property
    def wall_anchor_us(self):
        """Wall-clock (unix epoch) microseconds of this tracer's ``ts==0``
        origin — the anchor the cross-process merger uses to place every
        rank's relative timestamps on one shared timeline."""
        return (time.time_ns() - (time.perf_counter_ns() - self._t0_ns)) / 1e3

    @property
    def process_label(self):
        return self._process_label

    def set_process_label(self, label):
        """Name this process carries in Chrome-trace ``process_name``
        metadata (the dist kvstore sets ``mxnet_tpu rank N``)."""
        with self._lock:
            self._process_label = str(label)

    def _push(self, ev):
        with self._lock:
            tid = ev["tid"]
            if tid not in self._tid_names:
                self._tid_names[tid] = threading.current_thread().name
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(ev)

    def add_event(self, name, category, begin_ns, end_ns, attrs=None):
        """Record one complete ('X') event from raw perf_counter_ns stamps."""
        ev = {
            "name": name,
            "cat": category,
            "ph": "X",
            "ts": (begin_ns - self._t0_ns) / 1e3,
            "dur": (end_ns - begin_ns) / 1e3,
            "pid": self._pid,
            "tid": threading.get_ident(),
        }
        if attrs:
            ev["args"] = dict(attrs)
        self._push(ev)

    def add_instant(self, name, category, attrs=None):
        """Record an instant ('i') event at now."""
        ev = {
            "name": name,
            "cat": category,
            "ph": "i",
            "s": "t",
            "ts": (time.perf_counter_ns() - self._t0_ns) / 1e3,
            "pid": self._pid,
            "tid": threading.get_ident(),
        }
        if attrs:
            ev["args"] = dict(attrs)
        self._push(ev)

    def add_async(self, name, category, ph, id_, attrs=None, ts_ns=None):
        """Record one nestable async event (``ph`` in 'b'/'n'/'e') keyed by
        ``id`` — Perfetto renders same-(cat, id) events as one linked span
        tree, which is how serving requests thread queue → prefill →
        decode iterations → finish across scheduler iterations."""
        if ts_ns is None:
            ts_ns = time.perf_counter_ns()
        ev = {
            "name": name,
            "cat": category,
            "ph": ph,
            "id": str(id_),
            "ts": (ts_ns - self._t0_ns) / 1e3,
            "pid": self._pid,
            "tid": threading.get_ident(),
        }
        if attrs:
            ev["args"] = dict(attrs)
        self._push(ev)

    def thread_names(self):
        """{tid: thread name} for every thread that recorded an event."""
        with self._lock:
            return dict(self._tid_names)

    def events(self):
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()
            self._dropped = 0

    @property
    def dropped(self):
        return self._dropped

    def chrome_trace(self, extra_events=None):
        """The buffer as a Chrome-trace JSON object (a plain dict).

        ``extra_events`` lets callers (the profiler facade) merge additional
        event lists into the same timeline.  ``process_name`` and per-tid
        ``thread_name`` metadata (``ph:"M"``) ride along so single- and
        merged multi-rank traces are human-labeled in Perfetto.
        """
        events = [{
            "name": "process_name", "ph": "M", "pid": self._pid,
            "args": {"name": self._process_label},
        }]
        for tid, tname in sorted(self.thread_names().items()):
            events.append({
                "name": "thread_name", "ph": "M", "pid": self._pid,
                "tid": tid, "args": {"name": tname},
            })
        events.extend(self.events())
        if extra_events:
            events.extend(extra_events)
        trace = {"traceEvents": events, "displayTimeUnit": "ms"}
        if self._dropped:
            trace["otherData"] = {"droppedEvents": self._dropped}
        return trace


_TRACER = Tracer()


def get_tracer():
    return _TRACER


def enable():
    """Turn recording on.  Returns the previous state."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = True
    return prev


def disable():
    """Turn recording off.  Returns the previous state."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = False
    return prev


def enabled():
    return _ENABLED


def span(name, category="host", **attrs):
    """``with telemetry.span("step", "trainer", batch=32): ...`` — records a
    complete event when telemetry is enabled; a shared no-op otherwise."""
    if not _ENABLED:
        return NULL_SPAN
    return Span(_TRACER, name, category, attrs)


def instant(name, category="host", **attrs):
    """Zero-duration marker event."""
    if _ENABLED:
        _TRACER.add_instant(name, category, attrs)


def async_event(name, category, ph, id_, **attrs):
    """Flag-gated async ('b'/'n'/'e') event — request span trees."""
    if _ENABLED:
        _TRACER.add_async(name, category, ph, id_, attrs or None)


def clear():
    _TRACER.clear()


def chrome_trace(extra_events=None):
    return _TRACER.chrome_trace(extra_events)
