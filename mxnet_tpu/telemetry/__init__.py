"""mx.telemetry — unified runtime observability.

Per-process pieces (ISSUE 1 tentpole; reference anchors:
src/profiler/profiler.cc Chrome-trace writer + aggregate_stats.cc per-op
table):

- **spans** (`tracer`) — ``telemetry.span(name, category, **attrs)`` context
  manager recording begin/end host timestamps into a ring buffer;
  ``chrome_trace()`` exports genuine Chrome-trace JSON (``traceEvents`` with
  ``ph:"X"``, ``pid``/``tid``, ``cat``, ``args``, ``process_name``/
  ``thread_name`` metadata) for chrome://tracing / Perfetto.
- **metrics** (`metrics`) — process-global Counter/Gauge/Histogram registry
  (optionally labeled) with Prometheus-text and JSON exporters.
- **ledger** (`ledger`) — the per-op aggregate table mx.profiler renders.

The distributed observability plane (ISSUE 10) sits on top:

- **aggregate** — cross-process collection-dir protocol
  (``MXNET_TELEMETRY_DIR``): rank-tagged snapshot export at exit, merged
  Chrome trace (pid=rank) + merged Prometheus snapshot on rank 0 /
  ``tools/telemetry_report.py``; decode-pool workers ship counters back
  on their task-ack channel.
- **stepclock** — per-step data_wait/h2d/compute/comms/optimizer
  attribution from Trainer/TrainStep, ``mxnet_step_phase_seconds{phase=}``
  histograms, and the rolling input-/comms-/compute-bound verdict
  rendered by ``telemetry.report()``.
- **flightrec** — the always-on crash black box: bounded postmortem dumps
  on unhandled exceptions, deadline-exceeded, chaos exits, SIGTERM, and
  SIGUSR2 (``MXNET_FLIGHTREC*`` knobs).

The analytic performance observatory (ISSUE 12) completes the stack:

- **costmodel** — the per-executable compile/cost/memory ledger over
  every jit boundary the runtime owns (XLA's own flops/bytes/HBM numbers,
  no hardware needed), analytic MFU + roofline verdicts
  (``report(cost=True)``, BENCH rows), and the fits-per-shape
  ``estimate_memory`` API (``MXNET_COSTMODEL`` knobs).
- **httpd** — the live scrape plane (``MXNET_TELEMETRY_PORT``):
  ``/metrics`` Prometheus exposition, ``/statusz`` run status,
  ``/ledger.json``.

Instrumentation ships wired into the runtime chokepoints: op dispatch
(ops.registry), kvstore push/pull/allreduce, gluon.Trainer step phases,
DataLoader batch fetch, and checkpoint save/load.  The resilience layer
(mx.resilience, ISSUE 3) reports through the same registry:
``mxnet_resilience_{retries,faults_injected,deadline_exceeded,resumes,
fallbacks}_total`` and ``mxnet_resilience_retry_backoff_seconds``.  Everything is gated on
one flag: ``MXNET_TELEMETRY=1`` in the environment, ``telemetry.enable()``
at runtime, or implicitly via ``mx.profiler.start()``.  When the flag is
off, the dispatch hot path pays exactly one module-attribute check and the
non-hot paths one no-op span; nothing here imports jax.
"""

from __future__ import annotations

from .. import config
from . import ledger, metrics, tracer
from . import stepclock          # noqa: E402 — needs metrics loaded
from . import costmodel          # noqa: E402 — needs metrics loaded
from . import aggregate          # noqa: E402 — needs tracer/metrics/stepclock
from . import flightrec          # noqa: E402 — needs aggregate
from . import httpd              # noqa: E402 — needs metrics/costmodel
from . import perfgate           # noqa: E402 — needs config/costmodel
from .ledger import record_op
from .metrics import (  # noqa: F401
    DEFAULT_BUCKETS, REGISTRY, Counter, Gauge, Histogram, MetricsRegistry,
    counter, gauge, histogram, to_json, to_prometheus,
)
from .stepclock import STEP_CLOCK, StepClock  # noqa: F401
from .tracer import (  # noqa: F401
    NULL_SPAN, Span, Tracer, chrome_trace, disable, enable, enabled,
    get_tracer, instant, span,
)

__all__ = [
    "span", "instant", "enable", "disable", "enabled", "get_tracer",
    "chrome_trace", "clear",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "counter", "gauge", "histogram", "to_prometheus", "to_json",
    "DEFAULT_BUCKETS",
    "record_op", "record_dispatch", "ledger", "metrics", "tracer",
    "env_enabled",
    "aggregate", "flightrec", "stepclock", "StepClock", "STEP_CLOCK",
    "report", "costmodel", "httpd",
]


def report(clock=None, registry=None, cost=False):
    """The human-readable observability report: step-time attribution +
    bottleneck verdict + headline counters (stepclock.report), and — with
    ``cost=True`` — the analytic cost-ledger table (per-site flops,
    arithmetic intensity, peak-HBM, roofline verdict)."""
    out = stepclock.report(clock=clock, registry=registry)
    if cost:
        out += "\n" + costmodel.report_text()
    return out

# -- dispatch instrumentation (fed by ops.registry.invoke) -------------------
# Handles are created once; the hot path only observes into them.

_OP_COUNT = counter(
    "mxnet_op_dispatch_total", "Imperative op dispatches through ops.registry.")
_OP_SECONDS = histogram(
    "mxnet_op_dispatch_seconds", "Host-side dispatch latency per op.")
_HOOK_SECONDS = histogram(
    "mxnet_monitor_hook_seconds", "Monitor-hook overhead per dispatch.")


def record_dispatch(name, begin_ns, end_ns, hook_ns=0):
    """One imperative dispatch: counter + latency histogram + trace event +
    ledger row.  Callers gate on ``tracer._ENABLED`` so the disabled hot
    path never reaches here."""
    dt_s = (end_ns - begin_ns) / 1e9
    _OP_COUNT.inc()
    _OP_SECONDS.observe(dt_s)
    if hook_ns:
        _HOOK_SECONDS.observe(hook_ns / 1e9)
    tracer.get_tracer().add_event(name, "dispatch", begin_ns, end_ns)
    ledger.record_op(name, dt_s)


def clear():
    """Drop buffered trace events, ledger rows (op aggregate + cost), and
    the step-clock window (metrics keep counting — use REGISTRY.reset()
    to zero them)."""
    tracer.clear()
    ledger.clear()
    stepclock.STEP_CLOCK.reset()
    costmodel.LEDGER.clear()


def payload_bytes(value):
    """Best-effort byte size of an NDArray / jax array / (nested) list —
    used by the kvstore bytes-moved counters."""
    if isinstance(value, (list, tuple)):
        return sum(payload_bytes(v) for v in value)
    data = getattr(value, "_data", value)
    n = getattr(data, "nbytes", None)
    if n is not None:
        return int(n)
    # sparse NDArrays: data + indices ride separately
    total = 0
    for part in (getattr(value, "data", None), getattr(value, "indices", None)):
        if part is not None:
            total += payload_bytes(part)
    return total


# -- env switch --------------------------------------------------------------

_ENV_ENABLED = bool(config.get_int("MXNET_TELEMETRY", 0))
if _ENV_ENABLED:
    enable()

# observability plane (ISSUE 10): the flight recorder arms at import
# (always-on black box) and, with a collection dir configured, every
# process exports its rank-tagged telemetry shard at exit.
if config.get_int("MXNET_FLIGHTREC", 1):
    flightrec.install()
if config.get("MXNET_TELEMETRY_DIR"):
    aggregate.install_atexit()
# analytic observatory (ISSUE 12): the cost ledger arms from its env knob
# and the live scrape plane serves when a port is named (off by default)
costmodel.arm_from_env()
httpd.start_from_env()


def env_enabled():
    """True when MXNET_TELEMETRY turned telemetry on at import — the
    profiler facade then never turns it off on stop()."""
    return _ENV_ENABLED
