"""Cross-process telemetry aggregation — one merged view of a whole job.

Per-process telemetry (spans in a ring buffer, metrics in a registry) dies
with the process and tells you nothing about the *job*: which rank stalled,
whether the decode pool or the collective was the bottleneck, what the
fleet's aggregate throughput was.  This module is the collection-dir
protocol that fixes that (ISSUE 10 tentpole):

- **export** — :func:`export_snapshot` serializes this process's state
  (spans + thread names + wall-clock anchor, metric registry, ledger,
  step-clock summary) as one rank-tagged JSON file into
  ``MXNET_TELEMETRY_DIR``, committed atomically (write-then-rename, the
  checkpoint manifest discipline).  When the env knob is set, every
  process exports automatically at exit (and the flight recorder exports
  on crashes), so a job leaves one shard per rank with no wiring.
- **merge** — rank 0 (or ``tools/telemetry_report.py`` offline) loads the
  shards and renders ONE Chrome trace (:func:`merged_chrome_trace` —
  ``pid`` = rank, ``process_name``/``thread_name`` metadata, timestamps
  shifted onto a shared wall-clock timeline) and ONE Prometheus snapshot
  (:func:`merged_prometheus` — counters and histogram buckets summed
  across ranks, gauges summed as per-rank depths).
- **pool-worker shipping** — decode-pool workers have no exit hook worth
  waiting for; instead each task ack carries :func:`counter_deltas` (the
  counters that moved since the last ack) and the parent folds them in
  with :func:`absorb_counter_deltas` — zero extra IPC, riding the
  existing result channel.

The rank tag comes from the dist kvstore at bring-up (:func:`set_rank`,
which also labels the in-process Chrome trace) and falls back to
``MXNET_DIST_RANK``.  Nothing here imports jax.
"""

from __future__ import annotations

import atexit
import json
import os
import socket
import threading
import time

from .. import config
from . import costmodel, ledger, metrics, stepclock, tracer

__all__ = [
    "set_rank", "rank", "collection_dir", "snapshot", "export_snapshot",
    "load_snapshots", "merged_chrome_trace", "merged_registry",
    "merged_prometheus", "counter_deltas", "absorb_counter_deltas",
    "install_atexit",
]

SNAPSHOT_VERSION = 1
SNAPSHOT_PREFIX = "telemetry-"

_lock = threading.Lock()
_rank = None
_shipped: dict = {}        # (name, labels) -> counter value last shipped
_atexit_installed = False


def set_rank(r):
    """Tag this process with its job rank (dist kvstore bring-up calls
    this); also labels the local Chrome trace's process_name."""
    global _rank
    with _lock:
        _rank = None if r is None else int(r)
    if r is not None:
        tracer.get_tracer().set_process_label(f"mxnet_tpu rank {int(r)}")


def rank():
    """This process's rank: set_rank() value, else MXNET_DIST_RANK, else 0."""
    with _lock:
        if _rank is not None:
            return _rank
    return config.get_int("MXNET_DIST_RANK", 0)


def collection_dir():
    return config.get("MXNET_TELEMETRY_DIR")


# -- export -----------------------------------------------------------------

def snapshot():
    """This process's full telemetry state as one JSON-serializable dict —
    the collection-dir wire format."""
    tr = tracer.get_tracer()
    return {
        "version": SNAPSHOT_VERSION,
        "rank": rank(),
        "pid": os.getpid(),
        "host": socket.gethostname(),
        "time": time.time(),
        "process_label": tr.process_label,
        "wall_anchor_us": tr.wall_anchor_us,
        "events": tr.events(),
        "thread_names": {str(k): v for k, v in tr.thread_names().items()},
        "dropped": tr.dropped,
        "metrics": metrics.REGISTRY.export_state(),
        "ledger": {k: list(v) for k, v in ledger.snapshot().items()},
        "stepclock": stepclock.STEP_CLOCK.summary(),
        "costmodel": costmodel.LEDGER.snapshot(),
    }


def export_snapshot(directory=None, path=None):
    """Atomically write this process's snapshot into the collection dir
    (``telemetry-rank<R>-pid<P>.json``; re-exports from the same process
    replace their own file).  Returns the path, or None when no directory
    is configured."""
    if path is None:
        directory = directory or collection_dir()
        if not directory:
            return None
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(
            directory,
            f"{SNAPSHOT_PREFIX}rank{rank():05d}-pid{os.getpid()}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(snapshot(), f, default=str)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def install_atexit():
    """Register the exit-time export exactly once (telemetry.__init__
    calls this when MXNET_TELEMETRY_DIR is set)."""
    global _atexit_installed
    with _lock:
        if _atexit_installed:
            return
        _atexit_installed = True
    atexit.register(_atexit_export)


def _atexit_export():
    try:
        export_snapshot()
    except Exception:  # noqa: BLE001 — never break interpreter shutdown
        pass


# -- merge ------------------------------------------------------------------

def load_snapshots(directory=None, latest_per_rank=True):
    """Parse every ``telemetry-*.json`` shard in the collection dir.
    Corrupt/partial files are skipped (the atomic rename makes them rare:
    only a full pre-rename crash leaves a ``.tmp``, which is ignored).
    ``latest_per_rank`` keeps one shard per rank (newest export) so
    restarted jobs don't double-count dead incarnations."""
    directory = directory or collection_dir()
    out = []
    if not directory or not os.path.isdir(directory):
        return out
    for fn in sorted(os.listdir(directory)):
        if not (fn.startswith(SNAPSHOT_PREFIX) and fn.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, fn)) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(snap, dict):
            out.append(snap)
    if latest_per_rank:
        by_rank: dict = {}
        for s in out:
            r = s.get("rank", 0)
            if r not in by_rank \
                    or s.get("time", 0) > by_rank[r].get("time", 0):
                by_rank[r] = s
        out = [by_rank[r] for r in sorted(by_rank)]
    return out


def merged_chrome_trace(snapshots=None, directory=None):
    """One Chrome-trace dict from many rank snapshots: ``pid`` = rank,
    ``process_name``/``process_sort_index``/``thread_name`` metadata per
    rank, and every rank's relative timestamps shifted onto the shared
    wall-clock timeline (earliest tracer origin = ts 0)."""
    if snapshots is None:
        snapshots = load_snapshots(directory)
    events = []
    dropped = 0
    if not snapshots:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    base = min(s.get("wall_anchor_us", 0.0) for s in snapshots)
    for s in sorted(snapshots, key=lambda s: (s.get("rank") or 0,
                                              s.get("pid") or 0)):
        pid = s.get("rank")
        if pid is None:
            pid = s.get("pid", 0)
        shift = s.get("wall_anchor_us", base) - base
        label = s.get("process_label")
        if not label or label == "mxnet_tpu":   # default label: rank it
            label = f"mxnet_tpu rank {pid}"
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": label}})
        events.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                       "args": {"sort_index": pid}})
        for tid, tname in sorted((s.get("thread_names") or {}).items()):
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": int(tid), "args": {"name": tname}})
        for ev in s.get("events", ()):
            e = dict(ev)
            e["pid"] = pid
            if "ts" in e:
                e["ts"] = e["ts"] + shift
            events.append(e)
        dropped += int(s.get("dropped", 0) or 0)
    trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    if dropped:
        trace["otherData"] = {"droppedEvents": dropped}
    return trace


def merged_registry(snapshots):
    """A fresh MetricsRegistry holding the rank-summed union of every
    snapshot's metrics: counters/gauges sum, histogram buckets sum
    bucket-by-bucket (bounds match across ranks — same code registered
    them; on drift the tail bucket absorbs, keeping count/sum truthful)."""
    reg = metrics.MetricsRegistry()
    for s in snapshots:
        for e in s.get("metrics", ()):
            labels = e.get("labels") or None
            kind = e.get("kind")
            try:
                if kind == "counter":
                    v = e.get("value", 0) or 0
                    c = reg.counter(e["name"], e.get("help", ""),
                                    labels=labels)
                    if v:
                        c.inc(v)
                elif kind == "gauge":
                    reg.gauge(e["name"], e.get("help", ""),
                              labels=labels).inc(e.get("value", 0) or 0)
                elif kind == "histogram":
                    # registering with this rank's bounds would RAISE on
                    # cross-rank bounds drift (config/version skew during
                    # an elastic restart) and silently drop the series —
                    # reuse the registered histogram and let _absorb's
                    # tail-bucket fallback keep count/sum truthful
                    h = reg.get(e["name"], labels=labels)
                    if h is None:
                        h = reg.histogram(e["name"], e.get("help", ""),
                                          buckets=e["bounds"], labels=labels)
                    elif not isinstance(h, metrics.Histogram):
                        continue
                    h._absorb(e["bounds"], e["counts"], e["sum"], e["count"])
            except (KeyError, TypeError, ValueError):
                continue   # one malformed entry must not sink the merge
    return reg


def merged_prometheus(snapshots=None, directory=None):
    """The merged job-wide metric state in Prometheus text format."""
    if snapshots is None:
        snapshots = load_snapshots(directory)
    return merged_registry(snapshots).to_prometheus()


# -- pool-worker counter shipping (the decode-pool ack channel) -------------

def counter_deltas():
    """Counters that moved since the last call, as a small pickleable
    list ``[(name, labels, delta), ...]`` — a decode-pool worker attaches
    this to its task ack so its chaos/resilience/op counters reach the
    parent without a side channel."""
    out = []
    with _lock:
        for m in metrics.REGISTRY.all_metrics():   # no per-ack sort
            if m.kind != "counter":
                continue
            key = (m.name, m.labels)
            v = m.value
            d = v - _shipped.get(key, 0)
            if d:
                _shipped[key] = v
                out.append((m.name, dict(m.labels), d))
    return out


def absorb_counter_deltas(deltas):
    """Fold a worker's shipped counter deltas into this process's
    registry (get-or-create by name+labels, then add)."""
    for name, labels, d in deltas or ():
        if d > 0:
            metrics.REGISTRY.counter(name, labels=labels or None).inc(d)
