"""Metrics registry — Counter / Gauge / Histogram with text + JSON export.

The machine-readable half of mx.telemetry (the reference's
``aggregate_stats.cc`` table is human-only).  Metrics are process-global,
get-or-create by (name, labels), thread-safe, and export in two forms:

- ``to_prometheus()`` — the Prometheus text exposition format (``# HELP`` /
  ``# TYPE`` lines, ``_bucket{le="..."}`` cumulative histogram rows), so a
  scrape endpoint or a log line is one call away;
- ``to_json()`` — a plain dict for programmatic assertions and BENCH_* runs.

Labels (ISSUE 10): a metric may carry a fixed label set
(``histogram("mxnet_step_phase_seconds", labels={"phase": "comms"})``) —
each label combination is its own time series under one exported metric
name, with label values escaped per the exposition format (backslash,
double-quote, newline) and rows emitted in a stable (name, labels) order.
``export_state()``/``Histogram._absorb`` are the merge protocol the
cross-process aggregation plane (telemetry.aggregate) rides: counters and
histogram buckets sum across ranks, gauges sum (they are per-rank depths).

Stdlib-only; safe to import anywhere.
"""

from __future__ import annotations

import bisect
import json
import math
import threading

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "counter", "gauge", "histogram", "to_prometheus", "to_json",
           "DEFAULT_BUCKETS"]

# Latency-oriented defaults (seconds): 10us .. 10s, the span of one host
# dispatch up to one full checkpoint write.
DEFAULT_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _labels_key(labels):
    """Canonical hashable form of a label set: sorted (k, v) str pairs."""
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in dict(labels).items()))


def _escape_label_value(v):
    """Prometheus text-format label-value escaping: backslash, quote,
    newline (in that order — escaping the escape char first)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(labels, extra=()):
    """``{k="v",...}`` rendering of a labels tuple (+ trailing pairs like
    ``le``); empty string when there are no labels at all."""
    items = list(labels) + list(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in items)
    return "{" + body + "}"


def _norm_buckets(buckets):
    """Histogram bound normalization: floats, deduplicated, ascending,
    non-finite bounds dropped (the +Inf bucket is ALWAYS implicit — an
    explicit inf bound would render a duplicate +Inf row)."""
    bounds = tuple(sorted({float(b) for b in buckets
                           if math.isfinite(float(b))}))
    if not bounds:
        raise ValueError("histogram needs at least one finite bucket "
                         "boundary")
    return bounds


class Counter:
    """Monotonically increasing count (ops dispatched, bytes moved)."""

    kind = "counter"
    __slots__ = ("name", "help", "labels", "_value", "_lock")

    def __init__(self, name, help="", labels=None):  # noqa: A002
        self.name = name
        self.help = help
        self.labels = _labels_key(labels)
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, amount=1):
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self):
        return self._value

    def _reset(self):
        with self._lock:
            self._value = 0

    def snapshot(self):
        return {"type": self.kind, "help": self.help,
                "labels": dict(self.labels), "value": self._value}

    def render(self, lines):
        lines.append(f"{self.name}{_label_str(self.labels)} {self._value}")


class Gauge:
    """Point-in-time value (queue depth, loss scale)."""

    kind = "gauge"
    __slots__ = ("name", "help", "labels", "_value", "_lock")

    def __init__(self, name, help="", labels=None):  # noqa: A002
        self.name = name
        self.help = help
        self.labels = _labels_key(labels)
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = value

    def inc(self, amount=1):
        with self._lock:
            self._value += amount

    def dec(self, amount=1):
        with self._lock:
            self._value -= amount

    @property
    def value(self):
        return self._value

    def _reset(self):
        with self._lock:
            self._value = 0.0

    def snapshot(self):
        return {"type": self.kind, "help": self.help,
                "labels": dict(self.labels), "value": self._value}

    def render(self, lines):
        lines.append(f"{self.name}{_label_str(self.labels)} {self._value}")


class Histogram:
    """Distribution over fixed bucket boundaries (latency histograms).

    ``buckets`` are upper bounds in ascending order; an implicit +Inf bucket
    catches the tail (always emitted, exactly once — explicit non-finite
    bounds are normalized away).  Export follows Prometheus cumulative-bucket
    semantics.
    """

    kind = "histogram"
    __slots__ = ("name", "help", "labels", "buckets", "_counts", "_sum",
                 "_count", "_lock")

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS,  # noqa: A002
                 labels=None):
        self.name = name
        self.help = help
        self.labels = _labels_key(labels)
        self.buckets = _norm_buckets(buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value):
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def _reset(self):
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0

    def _raw(self):
        """(bounds, per-bucket counts incl. +Inf tail, sum, count) — the
        mergeable form telemetry.aggregate ships across processes."""
        with self._lock:
            return self.buckets, list(self._counts), self._sum, self._count

    def _absorb(self, bounds, counts, sum_, count):
        """Fold another process's raw state in.  Bounds are expected to
        match (same code, same registration); on drift the observations
        land in the +Inf tail so the count/sum stay truthful."""
        with self._lock:
            if tuple(float(b) for b in bounds) == self.buckets \
                    and len(counts) == len(self._counts):
                for i, c in enumerate(counts):
                    self._counts[i] += int(c)
            else:
                self._counts[-1] += int(count)
            self._sum += float(sum_)
            self._count += int(count)

    def snapshot(self):
        with self._lock:
            counts = list(self._counts)
            total, ssum = self._count, self._sum
        cum, buckets = 0, {}
        for bound, c in zip(self.buckets, counts):
            cum += c
            buckets[bound] = cum
        return {"type": self.kind, "help": self.help,
                "labels": dict(self.labels), "buckets": buckets,
                "sum": ssum, "count": total}

    def render(self, lines):
        snap = self.snapshot()
        for bound, cum in snap["buckets"].items():
            lines.append(
                f"{self.name}_bucket"
                f"{_label_str(self.labels, (('le', f'{bound:g}'),))} {cum}")
        lines.append(
            f"{self.name}_bucket"
            f"{_label_str(self.labels, (('le', '+Inf'),))} {snap['count']}")
        lines.append(
            f"{self.name}_sum{_label_str(self.labels)} {snap['sum']}")
        lines.append(
            f"{self.name}_count{_label_str(self.labels)} {snap['count']}")


class MetricsRegistry:
    """Get-or-create home for all metrics; one per process by default.

    Keyed by (name, labels): one metric name may carry several label
    combinations (each its own series) but exactly one kind.
    """

    def __init__(self):
        self._metrics: dict = {}
        self._kinds: dict = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labels=None, **kwargs):  # noqa: A002
        lk = _labels_key(labels)
        key = (name, lk)
        with self._lock:
            kind = self._kinds.get(name)
            if kind is not None and kind != cls.kind:
                raise TypeError(
                    f"metric {name!r} already registered as {kind}, "
                    f"requested {cls.kind}")
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help, labels=labels, **kwargs)
                self._metrics[key] = m
                self._kinds[name] = cls.kind
            return m

    def counter(self, name, help="", labels=None):  # noqa: A002
        return self._get_or_create(Counter, name, help, labels=labels)

    def gauge(self, name, help="", labels=None):  # noqa: A002
        return self._get_or_create(Gauge, name, help, labels=labels)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS,  # noqa: A002
                  labels=None):
        h = self._get_or_create(Histogram, name, help, labels=labels,
                                buckets=buckets)
        want = _norm_buckets(buckets)
        if h.buckets != want:
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{h.buckets}, requested {want}")
        return h

    def get(self, name, labels=None):
        return self._metrics.get((name, _labels_key(labels)))

    def collect(self):
        """All metrics in stable (name, labels) order."""
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def all_metrics(self):
        """All metrics, registration order (no sort) — for per-call scans
        on hot paths (the decode-pool ack channel) where render order is
        irrelevant."""
        with self._lock:
            return list(self._metrics.values())

    def reset(self):
        """Zero every metric in place (handles stay valid — instrumented
        modules hold module-level references)."""
        for m in self.collect():
            m._reset()

    def to_prometheus(self):
        lines = []
        last_name = None
        for m in self.collect():
            if m.name != last_name:   # HELP/TYPE once per name, not per row
                if m.help:
                    lines.append(f"# HELP {m.name} {m.help}")
                lines.append(f"# TYPE {m.name} {m.kind}")
                last_name = m.name
            m.render(lines)
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self, indent=None):
        out = {}
        for m in self.collect():
            out[m.name + _label_str(m.labels)] = m.snapshot()
        return json.dumps(out, indent=indent, sort_keys=True)

    def export_state(self):
        """Mergeable dump of every metric — the wire format of the
        cross-process aggregation protocol (telemetry.aggregate)."""
        out = []
        for m in self.collect():
            e = {"name": m.name, "labels": dict(m.labels), "kind": m.kind,
                 "help": m.help}
            if isinstance(m, Histogram):
                bounds, counts, ssum, count = m._raw()
                e.update(bounds=list(bounds), counts=counts, sum=ssum,
                         count=count)
            else:
                e["value"] = m.value
            out.append(e)
        return out


REGISTRY = MetricsRegistry()


def counter(name, help="", labels=None):  # noqa: A002
    return REGISTRY.counter(name, help, labels=labels)


def gauge(name, help="", labels=None):  # noqa: A002
    return REGISTRY.gauge(name, help, labels=labels)


def histogram(name, help="", buckets=DEFAULT_BUCKETS, labels=None):  # noqa: A002
    return REGISTRY.histogram(name, help, buckets=buckets, labels=labels)


def to_prometheus():
    return REGISTRY.to_prometheus()


def to_json(indent=None):
    return REGISTRY.to_json(indent=indent)
