"""Metrics registry — Counter / Gauge / Histogram with text + JSON export.

The machine-readable half of mx.telemetry (the reference's
``aggregate_stats.cc`` table is human-only).  Metrics are process-global,
get-or-create by name, thread-safe, and export in two forms:

- ``to_prometheus()`` — the Prometheus text exposition format (``# HELP`` /
  ``# TYPE`` lines, ``_bucket{le="..."}`` cumulative histogram rows), so a
  scrape endpoint or a log line is one call away;
- ``to_json()`` — a plain dict for programmatic assertions and BENCH_* runs.

Stdlib-only; safe to import anywhere.
"""

from __future__ import annotations

import bisect
import json
import threading

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "counter", "gauge", "histogram", "to_prometheus", "to_json",
           "DEFAULT_BUCKETS"]

# Latency-oriented defaults (seconds): 10us .. 10s, the span of one host
# dispatch up to one full checkpoint write.
DEFAULT_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class Counter:
    """Monotonically increasing count (ops dispatched, bytes moved)."""

    kind = "counter"
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name, help=""):  # noqa: A002 — prometheus field name
        self.name = name
        self.help = help
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, amount=1):
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self):
        return self._value

    def _reset(self):
        with self._lock:
            self._value = 0

    def snapshot(self):
        return {"type": self.kind, "help": self.help, "value": self._value}

    def render(self, lines):
        lines.append(f"{self.name} {self._value}")


class Gauge:
    """Point-in-time value (queue depth, loss scale)."""

    kind = "gauge"
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name, help=""):  # noqa: A002
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = value

    def inc(self, amount=1):
        with self._lock:
            self._value += amount

    def dec(self, amount=1):
        with self._lock:
            self._value -= amount

    @property
    def value(self):
        return self._value

    def _reset(self):
        with self._lock:
            self._value = 0.0

    def snapshot(self):
        return {"type": self.kind, "help": self.help, "value": self._value}

    def render(self, lines):
        lines.append(f"{self.name} {self._value}")


class Histogram:
    """Distribution over fixed bucket boundaries (latency histograms).

    ``buckets`` are upper bounds in ascending order; an implicit +Inf bucket
    catches the tail.  Export follows Prometheus cumulative-bucket semantics.
    """

    kind = "histogram"
    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_count",
                 "_lock")

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS):  # noqa: A002
        self.name = name
        self.help = help
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket boundary")
        self.buckets = bounds
        self._counts = [0] * (len(bounds) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value):
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def _reset(self):
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0

    def snapshot(self):
        with self._lock:
            counts = list(self._counts)
            total, ssum = self._count, self._sum
        cum, buckets = 0, {}
        for bound, c in zip(self.buckets, counts):
            cum += c
            buckets[bound] = cum
        return {"type": self.kind, "help": self.help, "buckets": buckets,
                "sum": ssum, "count": total}

    def render(self, lines):
        snap = self.snapshot()
        for bound, cum in snap["buckets"].items():
            lines.append(f'{self.name}_bucket{{le="{bound:g}"}} {cum}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {snap["count"]}')
        lines.append(f"{self.name}_sum {snap['sum']}")
        lines.append(f"{self.name}_count {snap['count']}")


class MetricsRegistry:
    """Get-or-create home for all metrics; one per process by default."""

    def __init__(self):
        self._metrics: dict = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, **kwargs):  # noqa: A002
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name, help=""):  # noqa: A002
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help=""):  # noqa: A002
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS):  # noqa: A002
        h = self._get_or_create(Histogram, name, help, buckets=buckets)
        want = tuple(sorted(float(b) for b in buckets))
        if h.buckets != want:
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{h.buckets}, requested {want}")
        return h

    def get(self, name):
        return self._metrics.get(name)

    def collect(self):
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def reset(self):
        """Zero every metric in place (handles stay valid — instrumented
        modules hold module-level references)."""
        for m in self.collect():
            m._reset()

    def to_prometheus(self):
        lines = []
        for m in self.collect():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            m.render(lines)
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self, indent=None):
        return json.dumps({m.name: m.snapshot() for m in self.collect()},
                          indent=indent, sort_keys=True)


REGISTRY = MetricsRegistry()


def counter(name, help=""):  # noqa: A002
    return REGISTRY.counter(name, help)


def gauge(name, help=""):  # noqa: A002
    return REGISTRY.gauge(name, help)


def histogram(name, help="", buckets=DEFAULT_BUCKETS):  # noqa: A002
    return REGISTRY.histogram(name, help, buckets=buckets)


def to_prometheus():
    return REGISTRY.to_prometheus()


def to_json(indent=None):
    return REGISTRY.to_json(indent=indent)
