"""Live telemetry HTTP plane — scrape the process while it runs.

PR 9's observability plane exports snapshot files at process exit; a
router balancing replicas (ROADMAP 2) or an operator watching a training
job needs the *live* registry.  This is the stdlib answer (ISSUE 12
tentpole part 4): a ``ThreadingHTTPServer`` on a daemon thread, off by
default, armed by ``MXNET_TELEMETRY_PORT=<port>`` (0 picks an ephemeral
port — tests) or :func:`start`:

- ``GET /metrics``     — the Prometheus text exposition of the live
  ``MetricsRegistry`` (exactly ``telemetry.to_prometheus()``: the scrape
  surface the least-loaded router dispatches on — serving queue/slot/
  TTFT gauges included because they live in the same registry);
- ``GET /statusz``     — JSON run status: rank/world/pid, resolved
  ``MXNET_*`` knobs (non-default ones flagged), the rolling step-clock
  summary + bottleneck verdict, serving queue/slot/block gauges, and the
  telemetry/costmodel arming states;
- ``GET /ledger.json`` — the cost ledger (per-executable flops/bytes/
  peak-HBM records) plus the per-op aggregate ledger;
- ``GET /healthz``     — liveness probe fed by the resilience heartbeat
  (ISSUE 13): 200 + ``{phase, heartbeat_age_s}`` while the armed beater
  is fresh, 503 once it goes stale past ``MXNET_ROUTER_HANG_S`` — what
  the serving router (and any external load balancer) scrapes to decide
  a replica is still worth dispatching to.  A process with no heartbeat
  armed answers 200 (the HTTP reply itself proves the process serves);
- ``GET /``            — a plain-text index.

Scrapes never block instrumentation: handlers only *read* the registry
(each metric snapshots under its own lock), and rendering happens on the
server's per-connection threads.  Nothing here imports jax.
"""

from __future__ import annotations

import json
import threading

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import config
from . import costmodel, ledger, metrics, stepclock

__all__ = ["start", "stop", "running", "port", "start_from_env"]

_lock = threading.Lock()
_server = None
_thread = None

_SERVING_GAUGES = (
    "mxnet_serving_queue_depth", "mxnet_serving_active_slots",
    "mxnet_serving_free_blocks",
)


def _statusz():
    import os
    from . import aggregate
    knobs = {}
    for name, current, default, _doc in config.describe():
        row = {"value": current}
        if current != default:
            row["default"] = default
        knobs[name] = row
    serving = {}
    for name in _SERVING_GAUGES:
        m = metrics.REGISTRY.get(name)
        if m is not None:
            serving[name] = m.value
    from . import tracer
    return {
        "pid": os.getpid(),
        "rank": aggregate.rank(),
        "world": config.get_int("MXNET_DIST_NUM_WORKERS", 1),
        "telemetry_enabled": tracer._ENABLED,
        "costmodel_armed": costmodel.armed(),
        "perfgate": _perfgate_verdict(),
        "stepclock": stepclock.STEP_CLOCK.summary(),
        "serving": serving,
        "knobs": knobs,
    }


def _ledger_json():
    return {
        "costmodel": costmodel.LEDGER.snapshot(),
        "costmodel_sites": costmodel.LEDGER.site_summary(),
        "ops": {k: list(v) for k, v in ledger.snapshot().items()},
    }


def _perfgate():
    """(status_code, body) — the live snapshot-vs-committed-baseline
    delta (ISSUE 16 satellite).  Reuses the gate's diff engine over the
    live cost ledger: only per-site analytic invariants that overlap the
    baseline lanes are compared (a live process runs one workload, not
    the lane matrix).  404 when no baseline is committed."""
    import os
    from . import perfgate
    path = perfgate.default_baseline_path()
    if not os.path.exists(path):
        return 404, {"error": "no committed baseline", "path": path}
    try:
        doc = perfgate.load_baseline(path)
    except perfgate.BaselineError as e:
        return 500, {"error": str(e)}
    counters = {}
    for m in metrics.REGISTRY.collect():
        if m.kind == "counter" and getattr(m, "value", 0):
            counters[m.name] = m.value
    delta = perfgate.live_delta(doc, costmodel.LEDGER.site_summary(),
                                counters)
    delta["baseline_path"] = path
    return 200, delta


def _perfgate_verdict():
    """One-word gate state for the /statusz row; never raises."""
    try:
        code, delta = _perfgate()
        if code == 404:
            return "no-baseline"
        if code != 200:
            return "baseline-error"
        if not delta["ok"]:
            return "drift"
        return "ok" if delta.get("overlap_sites") else "no-overlap"
    except Exception:  # noqa: BLE001 — a status row must not kill statusz
        return "error"


def _healthz():
    """(status_code, body_dict) from the resilience heartbeat.  Stale =
    the armed beater has not landed a beat within MXNET_ROUTER_HANG_S
    (the same staleness bound the router's out-of-band hb-file check
    uses, so the two probes agree)."""
    from ..resilience import heartbeat
    st = heartbeat.status()
    st["ok"] = True
    if st["armed"]:
        stale_s = config.get_float("MXNET_ROUTER_HANG_S", 20.0)
        age = st["heartbeat_age_s"]
        if stale_s > 0 and (age is None or age > stale_s):
            st["ok"] = False
            return 503, st
    return 200, st


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet-tpu-telemetry"

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = metrics.to_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/statusz":
                body = json.dumps(_statusz(), indent=1,
                                  default=str).encode()
                ctype = "application/json"
            elif path == "/ledger.json":
                body = json.dumps(_ledger_json(), default=str).encode()
                ctype = "application/json"
            elif path == "/healthz":
                code, health = _healthz()
                body = json.dumps(health).encode()
                ctype = "application/json"
                if code != 200:
                    # send_error would wrap the body in HTML; a liveness
                    # probe wants the JSON payload with the 503
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
            elif path == "/perfgate.json":
                code, delta = _perfgate()
                body = json.dumps(delta, indent=1, sort_keys=True,
                                  default=str).encode()
                ctype = "application/json"
                if code != 200:
                    # same non-HTML contract as /healthz: the scraper
                    # wants the JSON payload with the 404/500
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
            elif path == "/":
                body = (b"mxnet_tpu telemetry\n"
                        b"  /metrics     Prometheus exposition\n"
                        b"  /statusz     run status JSON\n"
                        b"  /ledger.json cost + op ledgers\n"
                        b"  /healthz     heartbeat liveness probe\n"
                        b"  /perfgate.json live vs committed perf baseline\n")
                ctype = "text/plain; charset=utf-8"
            else:
                self.send_error(404, "unknown endpoint")
                return
        except Exception as e:  # noqa: BLE001 — a scrape bug must not 500-loop
            self.send_error(500, f"{type(e).__name__}: {e}"[:200])
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # noqa: ARG002 — no stderr chatter
        pass


def start(port=None, host="0.0.0.0"):
    """Start the daemon-thread server (idempotent); returns the bound
    port.  ``port=0`` binds an ephemeral port (tests / parallel ranks).
    Asking for a DIFFERENT specific port while a server is already
    running (e.g. auto-started from ``MXNET_TELEMETRY_PORT``) raises —
    silently returning the old port would leave a router scraping a port
    nothing listens on."""
    global _server, _thread
    with _lock:
        if _server is not None:
            bound = _server.server_address[1]
            if port not in (None, 0, bound):
                raise RuntimeError(
                    f"telemetry httpd already serving on port {bound}; "
                    f"stop() it before rebinding to {port}")
            return bound
        if port is None:
            port = config.get_int("MXNET_TELEMETRY_PORT", -1)
            if port < 0:
                return None
        srv = ThreadingHTTPServer((host, int(port)), _Handler)
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever,
                             name="mxnet-telemetry-httpd", daemon=True)
        t.start()
        _server, _thread = srv, t
        return srv.server_address[1]


def stop():
    """Shut the server down and release the port (idempotent)."""
    global _server, _thread
    with _lock:
        srv, t = _server, _thread
        _server = _thread = None
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if t is not None:
        t.join(timeout=5)


def running():
    with _lock:
        return _server is not None


def port():
    with _lock:
        return None if _server is None else _server.server_address[1]


def start_from_env():
    """telemetry.__init__ calls this at import: serve only when the env
    knob names a port."""
    if config.get("MXNET_TELEMETRY_PORT") is not None:
        return start()
    return None
