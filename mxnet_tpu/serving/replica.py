"""Replica worker — one ``ServingEngine`` behind a line-framed socket RPC
(ISSUE 13 tentpole, worker half).

The router (``serving.router``) supervises N of these as subprocesses.
Each worker:

- binds a localhost TCP socket and publishes ``{pid, port}`` to an atomic
  port file in the tier workdir (``replica-<i>.json``) — which is how a
  RESTARTED router finds and re-adopts a live replica (stdio pipes die
  with the parent; a socket survives it);
- beats the ``resilience.heartbeat`` file protocol (the router injects
  ``MXNET_ELASTIC_HEARTBEAT_DIR`` + ``MXNET_DIST_RANK``), walking
  ``spawned → bringup → running → done`` so staleness is the router's
  hang signal and ``telemetry.httpd``'s ``/healthz`` answers 503 when the
  process wedges;
- serves one connection at a time (the router is the only client); a
  dropped connection loops back to ``accept`` so a successor router can
  reconnect.

Protocol (one JSON object per ``\\n``-terminated line, UTF-8):

    router -> replica:
      {"op": "submit", "rid": str, "prompt": [int], "max_new_tokens": N,
       "deadline_s": float|null}
      {"op": "cancel", "rid": str}          # hedge loser
      {"op": "ping"}                        # load refresh
      {"op": "shutdown"}                    # graceful drain end

    replica -> router:
      {"type": "hello", "pid", "index", "slots", "load": [q, a, f]}
      {"type": "accepted", "rid", "load"}
      {"type": "ack", "rid", "ok": true, "tokens": [...], "load"}
      {"type": "ack", "rid", "ok": false, "error": cls, "message", "load"}
      {"type": "pong", "load"}

``load`` is the engine's ATOMIC ``(queue_depth, active_slots,
free_blocks)`` snapshot — the least-loaded dispatch signal, shipped on
every ack so the router needs no extra scrape round-trip (the live
``/metrics`` plane stays available for external balancers).

Exactly-once discipline: completed replies are kept in a bounded
``done`` cache keyed by the ROUTER's rid, so a resubmitted rid — a
restarted router re-dispatching its journal, or a retry racing a slow
ack — answers from the cache instead of recomputing, and a rid still in
flight re-attaches instead of double-submitting.  The ``serving.reply``
chaos site fires after a result is computed but BEFORE its ack is
written: kind 'exit' there is the death window a router retry must cover
without the client ever seeing duplicate tokens.

The RPC/supervision half is deliberately engine-agnostic: anything with
``submit(prompt, max_new_tokens, deadline_s) -> handle`` / ``load()`` /
``stop()`` serves, which is how the jax-free stub replica in the fast
router tests drives the exact same protocol code as the llama CLI below.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import queue as _queue
import socket
import sys
import threading

from .. import config
from .. import telemetry as _tel
from ..telemetry import tracer as _ttrace
from ..base import MXNetError
from ..resilience import chaos as _chaos
from ..resilience import heartbeat as _hb

__all__ = ["ReplicaServer", "port_file_path", "read_port_file", "main"]

HOST = "127.0.0.1"
DONE_CACHE = 256          # completed replies kept for rid dedup


def port_file_path(workdir, index):
    return os.path.join(workdir, f"replica-{int(index):04d}.json")


def read_port_file(workdir, index):
    """Parse a replica's published ``{pid, port, index}`` record, or None
    (absent / torn — atomic renames make torn rare)."""
    try:
        with open(port_file_path(workdir, index)) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) and "port" in rec else None


class _Pending:
    __slots__ = ("handle", "cancelled")

    def __init__(self, handle):
        self.handle = handle
        self.cancelled = False


class ReplicaServer:
    """Serve one engine over the line-framed RPC above."""

    def __init__(self, engine, workdir, index):
        self._engine = engine
        self._workdir = os.path.abspath(workdir)
        self._index = int(index)
        self._lsock = None
        self._conn = None            # current router connection (_wlock)
        self._lock = threading.Lock()       # pending/done maps + _stop
        self._wlock = threading.Lock()      # connection swap + line writes
        self._pending = {}                  # rid -> _Pending
        self._done = collections.OrderedDict()   # rid -> cached ack
        self._load_at = 0.0                 # _load TTL cache
        self._load_val = [0, 0, 0]
        self._outq = _queue.SimpleQueue()   # replies -> sender thread
        self._sender = None
        self._stop = False

    def attach_engine(self, engine):
        """Late-bind the engine (the CLI binds the socket first so the
        port file exists while the model still builds)."""
        self._engine = engine

    # -- wire ---------------------------------------------------------------

    def _load(self):
        """Engine load triple for acks, cached ~5ms: engine.load() takes
        the scheduler lock, and a submit/ack burst taking it per line
        convoys with the decode loop's long lock holds.  The cache races
        benignly across reader/waiter threads — load is advisory, and a
        5ms-stale triple is fresher than the router's ping fallback."""
        import time as _time
        now = _time.monotonic()
        if now - self._load_at > 0.005:
            try:
                self._load_val = list(self._engine.load())  # graftcheck: ignore[GC04] — advisory TTL cache; concurrent writers both store a valid fresh triple
            except Exception:  # noqa: BLE001 — load is advisory
                pass
            self._load_at = now  # graftcheck: ignore[GC04] — same benign TTL race as _load_val
        return list(self._load_val)

    def _send(self, obj):
        """Queue one reply for the sender thread.  Waiter/reader threads
        do NO wire work — the json+syscall cost on a completion burst
        otherwise interleaves with the scheduler thread's GIL windows
        between decode dispatches (measured as inter-step gaps).  A
        reply that finds no live router connection is dropped; the done
        cache answers the successor's resubmit."""
        self._outq.put(obj)
        return True

    def _sender_loop(self):
        """Drain the reply queue onto the current connection — batches a
        burst into one sendall, serializes writes without a lock convoy."""
        while True:
            obj = self._outq.get()
            if obj is None:
                return
            batch = [obj]
            try:
                while True:
                    nxt = self._outq.get_nowait()
                    if nxt is None:
                        return
                    batch.append(nxt)
            except _queue.Empty:
                pass
            data = "".join(json.dumps(o) + "\n" for o in batch).encode()
            with self._wlock:
                conn = self._conn
                if conn is None:
                    continue
                try:
                    conn.sendall(data)
                except OSError:
                    self._conn = None

    def bind(self):
        """Listen on an ephemeral localhost port and publish the port
        file (write-then-rename: a router never reads a torn record)."""
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.bind((HOST, 0))
        self._lsock.listen(4)
        port = self._lsock.getsockname()[1]
        os.makedirs(self._workdir, exist_ok=True)
        path = port_file_path(self._workdir, self._index)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"pid": os.getpid(), "port": port,
                       "index": self._index}, f)
        os.replace(tmp, path)
        return port

    # -- request lifecycle --------------------------------------------------

    def _waiter(self, rid, handle):
        """Block for one request's result and ack it (one daemon thread
        per in-flight request; bounded by the router's admission
        control).  The engine's own Deadline bounds the wait, so a dead
        scheduler thread becomes an error ack, not a leaked thread."""
        try:
            # wait() + drained result(): no Deadline worker thread per
            # request (handle.wait exists for exactly this caller)
            if hasattr(handle, "wait"):
                handle.wait(config.get_float(
                    "MXNET_KVSTORE_TIMEOUT_S", 300.0))
            tokens = handle.result(timeout=5.0)
            reply = {"type": "ack", "rid": rid, "ok": True,
                     "tokens": [int(t) for t in tokens]}
        except Exception as exc:  # noqa: BLE001 — shipped to the router
            reply = {"type": "ack", "rid": rid, "ok": False,
                     "error": type(exc).__name__,
                     "message": str(exc)[:300]}
        with self._lock:
            p = self._pending.pop(rid, None)
            cancelled = p is not None and p.cancelled
            if not cancelled:
                self._done[rid] = reply
                while len(self._done) > DONE_CACHE:
                    self._done.popitem(last=False)
        if cancelled:
            return            # hedge loser: computed, deliberately unacked
        # the dedup-on-retry window: the result exists, the ack does not.
        # kind 'exit' here is the replica death a router resubmission must
        # make invisible (the survivor recomputes token-identically)
        if _chaos._ACTIVE:
            _chaos.hit("serving.reply", rid=rid)
        _ttrace.async_event("replica_reply", "router.request", "n", rid,
                            replica=self._index, ok=reply["ok"])
        self._send(dict(reply, load=self._load()))

    def _submit_one(self, rec):
        """Admit one submit record.  Returns a CACHED final ack when the
        rid already completed (restarted-router resubmit: recomputing
        would be wasted prefill, acking different content would break
        exactly-once), else None — a rid already in flight re-attaches
        (the waiter acks to whichever connection is current).  The
        accepted ack is the caller's job (batched)."""
        rid = str(rec["rid"])
        with self._lock:
            cached = self._done.get(rid)
            pending = rid in self._pending
        if cached is not None:
            return cached
        if pending:
            return None
        _ttrace.async_event("replica_accept", "router.request", "n",
                            rid, replica=self._index)
        handle = self._engine.submit(
            list(rec.get("prompt") or []),
            max_new_tokens=int(rec.get("max_new_tokens", 32)),
            deadline_s=rec.get("deadline_s"))
        with self._lock:
            self._pending[rid] = _Pending(handle)
        threading.Thread(target=self._waiter, args=(rid, handle),
                         daemon=True,
                         name=f"mx-replica-wait-{rid}").start()
        return None

    def _handle(self, msg):
        """Dispatch one parsed request line.  Returns False to end the
        accept loop (shutdown)."""
        op = msg.get("op")
        if op == "submit":
            cached = self._submit_one(msg)
            if cached is not None:
                self._send(dict(cached, load=self._load()))
            else:
                self._send({"type": "accepted", "rid": msg.get("rid"),
                            "load": self._load()})
        elif op == "submit_batch":
            reqs = msg.get("reqs") or []
            for rec in reqs:
                cached = self._submit_one(rec)
                if cached is not None:
                    self._send(dict(cached, load=self._load()))
            self._send({"type": "accepted",
                        "rids": [r.get("rid") for r in reqs],
                        "load": self._load()})
        elif op == "cancel":
            rid = str(msg.get("rid"))
            with self._lock:
                p = self._pending.get(rid)
                if p is not None:
                    p.cancelled = True
                self._done.pop(rid, None)
            _ttrace.async_event("replica_cancel", "router.request", "n",
                                rid, replica=self._index)
            # cancels are rare (hedge losers) — an append-only log line
            # makes "the loser was really cancelled" externally checkable
            try:
                with open(os.path.join(
                        self._workdir,
                        f"cancels-{self._index:04d}.log"), "a") as f:
                    f.write(rid + "\n")
            except OSError:
                pass
        elif op == "ping":
            self._send({"type": "pong", "load": self._load()})
        elif op == "shutdown":
            self._send({"type": "bye"})
            return False
        return True

    def _serve_conn(self, conn):
        """One router connection: hello, then request lines until EOF or
        shutdown.  Returns False when the worker should exit."""
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with self._wlock:
            self._conn = conn
        self._send({"type": "hello", "pid": os.getpid(),
                    "index": self._index,
                    "slots": getattr(self._engine, "max_batch", None),
                    "load": self._load()})
        keep = True
        try:
            with conn.makefile("r", encoding="utf-8") as rfile:
                for line in rfile:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        msg = json.loads(line)
                    except ValueError:
                        continue      # torn line from a dying router
                    keep = self._handle(msg)
                    if not keep:
                        break
        except OSError:
            pass
        with self._wlock:
            if self._conn is conn:
                self._conn = None
        try:
            conn.close()
        except OSError:
            pass
        return keep

    def run(self):
        """Accept loop: one router at a time; a dropped router loops back
        to accept so its restarted successor can re-adopt this replica."""
        if self._lsock is None:
            self.bind()
        self._sender = threading.Thread(target=self._sender_loop,
                                        daemon=True,
                                        name="mx-replica-send")
        self._sender.start()
        while True:
            with self._lock:
                if self._stop:
                    break
            try:
                conn, _addr = self._lsock.accept()
            except OSError:
                break
            if not self._serve_conn(conn):
                break
        self.close()

    def close(self):
        with self._lock:
            self._stop = True
        self._outq.put(None)        # sender sentinel
        sock, self._lsock = self._lsock, None  # graftcheck: ignore[GC04] — _lsock swap races only with accept(), whose OSError path is the intended wakeup
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        try:
            self._engine.stop()
        except Exception:  # noqa: BLE001 — shutdown is best-effort
            pass


# -- CLI (the real-model worker the router spawns) ---------------------------

def _build_engine(args):
    """Deterministic llama build: every replica spawned with the same
    (model, vocab, seed) holds bit-identical weights, which is what makes
    a retried request's re-prefill on a survivor token-identical.  The
    draft model (speculative decoding, ``--draft`` /
    ``MXNET_SERVING_DRAFT``) builds the same way from its own zoo config
    name — same seed, same vocab — so every replica speculates
    identically too."""
    import numpy as np
    import mxnet_tpu as mx
    from ..gluon.model_zoo import llama
    from .engine import ServingEngine

    def build(name):
        mx.random.seed(args.seed)
        np.random.seed(args.seed)
        net = llama.llama_model(name, vocab_size=args.vocab)
        net.initialize(mx.initializer.Normal(0.05))
        net(mx.nd.array(np.zeros((1, 4), np.int32)))  # finish deferred init
        return net

    net = build(args.model)
    draft = build(args.draft) if args.draft else None
    eng = ServingEngine(
        net, eos_id=args.eos, max_batch=args.max_batch,
        block_tokens=args.block_tokens, max_seq=args.max_seq,
        prefill_tokens=args.prefill_tokens,
        prefix_cache=args.prefix_cache, draft_model=draft,
        spec_k=args.spec_k)
    eng.start()
    return eng


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="serving replica worker (spawned by serving.router)")
    ap.add_argument("--workdir",
                    default=config.get("MXNET_ROUTER_DIR"))
    ap.add_argument("--index", type=int,
                    default=config.get_int("MXNET_ROUTER_INDEX", 0))
    ap.add_argument("--model", default="llama_tiny")
    ap.add_argument("--vocab", type=int, default=101)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--eos", type=int, default=-1)
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--block-tokens", type=int, default=None)
    ap.add_argument("--max-seq", type=int, default=None)
    ap.add_argument("--prefill-tokens", type=int, default=None)
    ap.add_argument("--draft", default=config.get("MXNET_SERVING_DRAFT"),
                    help="draft-model zoo config for speculative decoding "
                         "(MXNET_SERVING_DRAFT; unset = off)")
    ap.add_argument("--spec-k", type=int, default=None,
                    help="draft tokens per iteration (MXNET_SERVING_SPEC_K)")
    ap.add_argument("--prefix-cache", type=int,
                    default=config.get_int("MXNET_SERVING_PREFIX_CACHE", 0),
                    help="1 arms paged-KV prefix caching "
                         "(MXNET_SERVING_PREFIX_CACHE)")
    args = ap.parse_args(argv)
    if not args.workdir:
        raise MXNetError("replica worker needs --workdir "
                         "(or MXNET_ROUTER_DIR in the env)")
    # GIL switch interval: the scheduler thread re-acquires the GIL
    # after EVERY XLA dispatch returns; at the default 5ms interval a
    # submit burst on the reader thread turns each ~1ms prefill into
    # ~16ms of convoy (measured — it halved the 2-replica scale-out
    # ratio).  1ms bounds the handoff; going lower starts preempting
    # the scheduler thread's own host work between dispatches (0.5ms
    # measured ~15% slower end-to-end).
    sys.setswitchinterval(0.001)
    _tel.aggregate.set_rank(args.index)
    _ttrace.get_tracer().set_process_label(
        f"mxnet_tpu replica {args.index}")
    _hb.start()
    _hb.set_phase("bringup")
    # bind + publish the port file BEFORE the (slow) model build: a
    # router can then connect — and a RESTARTED router re-adopt — a
    # still-compiling replica; early submits just wait in the socket
    # buffer until the accept loop starts below
    srv = ReplicaServer(None, args.workdir, args.index)
    srv.bind()
    try:
        srv.attach_engine(_build_engine(args))
    except Exception as exc:  # noqa: BLE001 — surfaced to the router
        _hb.mark_failed(exc)
        raise
    _hb.set_phase("running")
    srv.run()
    _hb.mark_done()
    return 0


if __name__ == "__main__":
    sys.exit(main())
